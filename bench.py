#!/usr/bin/env python
"""Benchmark entry point (driver contract: ONE JSON line on stdout).

Runs the scheduler_perf SchedulingBasic workload (reference:
test/integration/scheduler_perf, 5000 nodes / 5000 pods scale from
config/performance-config.yaml) through the FULL pipeline — store -> watch
-> informers -> queue -> TPU batch Filter/Score/Assign -> assume -> bind —
and reports end-to-end scheduling throughput.

Baseline: the reference tree publishes no absolute numbers (BASELINE.md);
upstream Kubernetes scheduler_perf results for the 5k-node SchedulingBasic
tier sit around ~300 pods/s steady-state on a large single box (public
perf-dash data; the in-tree comment scheduler_perf_test.go:956 notes a
~10 pods/s worst case).  vs_baseline uses 300 pods/s as the reference
point.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_PODS_PER_SEC = 300.0

N_NODES = int(os.environ.get("BENCH_NODES", "5000"))
N_PODS = int(os.environ.get("BENCH_PODS", "20000"))
BATCH = int(os.environ.get("BENCH_BATCH", "2048"))


def main() -> None:
    from kubernetes_tpu.ops.flatten import Caps
    from kubernetes_tpu.perf import load_workloads, run_named_workload

    import copy
    cfg = copy.deepcopy(load_workloads()["SchedulingBasicLarge"])
    for op in cfg["workloadTemplate"]:
        if op["opcode"] == "createNodes":
            op["count"] = N_NODES
        elif op["opcode"] == "createPods":
            op["count"] = N_PODS
        elif op["opcode"] == "barrier":
            op["timeout"] = 900.0

    n_cap = max(1024, -(-int(N_NODES * 1.1) // 256) * 256)  # ~10% headroom
    caps = Caps(n_cap=n_cap,
                l_cap=256, kl_cap=62, t_cap=16, pt_cap=16, s_cap=3,
                sg_cap=16, asg_cap=16)
    # multiple full passes, report the MEDIAN: host-thread scheduling noise
    # swings individual runs ~20% in either direction, and the first run
    # additionally pays compile/trace warmup
    runs = []
    t0 = time.monotonic()
    for _ in range(max(1, int(os.environ.get("BENCH_RUNS", "3")))):
        summary, stats = run_named_workload(cfg, tpu=True, caps=caps,
                                            batch_size=BATCH)
        if not stats.get("barrier_ok", False):
            print(json.dumps({"metric": "scheduler_perf_throughput",
                              "value": 0.0, "unit": "pods/s",
                              "vs_baseline": 0.0,
                              "error": "pods left unscheduled",
                              "detail": summary.to_dict()}))
            sys.exit(1)
        runs.append(summary)
    wall = time.monotonic() - t0
    summary = sorted(runs, key=lambda s: s.average)[len(runs) // 2]
    value = summary.average
    print(json.dumps({
        "metric": "scheduler_perf_throughput",
        "value": round(value, 1),
        "unit": "pods/s",
        "vs_baseline": round(value / BASELINE_PODS_PER_SEC, 2),
        "detail": {"nodes": N_NODES, "pods": N_PODS, "batch": BATCH,
                   "wall_s": round(wall, 1), "runs": len(runs),
                   "averages": [round(s.average, 1) for s in runs],
                   **summary.to_dict()},
    }))


if __name__ == "__main__":
    main()
