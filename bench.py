#!/usr/bin/env python
"""Benchmark entry point (driver contract: ONE JSON line on stdout).

Runs the scheduler_perf workloads (reference: test/integration/
scheduler_perf, config/performance-config.yaml shapes) through the FULL
pipeline — store -> watch -> informers -> queue -> TPU batch
Filter/Score/Assign -> assume -> bind — and reports end-to-end
scheduling throughput.

Headline metric: Scheduling100k (BENCH_HEAD_NODES=100000 nodes /
BENCH_HEAD_PODS=200000 pods) through the SHARDED backend (`--backend
sharded`, the default; parallel/backend.py — node tensors partitioned
across the mesh, conflict matrices resolved per pod slab via
reduce-scatter), one fresh-subprocess pass with the device cost census
armed so `tpu_wave_collective_bytes` rides in the row.  `--backend
tpu`/BENCH_BACKEND override the backend kind.

Tracked configs (BASELINE.md): unless BENCH_SUITE=basic, one pass each
of the hard workloads also runs and lands in detail.configs —
  SchedulingBasicSingleChip  the BENCH_r01-r05 trajectory row: 5k nodes,
                          single-chip, median of BENCH_RUNS
                          fresh-subprocess passes
  Scheduling100k          100k nodes / 200k pods SINGLE-CHIP (the
                          headline's direct A/B)
  SchedulingPodAntiAffinity  5k nodes / 5k anti-affinity pods
  TopologySpreading       1k nodes / 3 zones / 5k DoNotSchedule pods
  CoschedulingGang        5k nodes / 10k pods in 1k PodGroups

Baseline: the reference tree publishes no absolute numbers (BASELINE.md);
upstream Kubernetes scheduler_perf results for the 5k-node SchedulingBasic
tier sit around ~300 pods/s steady-state on a large single box (public
perf-dash data; the in-tree comment scheduler_perf_test.go:956 notes a
~10 pods/s worst case).  vs_baseline uses 300 pods/s as the reference
point.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_PODS_PER_SEC = 300.0


def scaled_timeout(pods: int | None, base: float = 900.0) -> float:
    """Barrier/freeze budget scaled with the measured pod count.

    The flat 900 s default was sized for ~50k-pod tiers; a 200k-pod
    headline pass under bad tunnel weather can legitimately need more
    wall than that (the r06 run expired its 1800 s barrier mid-drain),
    while small paced rows should keep failing fast.  The scale term is
    ~100 pods/s — the worst healthy whole-run rate observed on the
    1-CPU box at the 100k tier — plus a fixed setup allowance; `base`
    stays the floor so no existing config gets a SHORTER budget."""
    if not pods:
        return base
    return max(base, 60.0 + pods / 100.0)

N_NODES = int(os.environ.get("BENCH_NODES", "5000"))
# 50k pods: at ~10k+ pods/s a 20k-pod run is half pipeline ramp; 50k gives
# ~5s of steady state under the 1s sampling window
N_PODS = int(os.environ.get("BENCH_PODS", "50000"))
# 16384 is the largest batch whose [P,N] working set fits v5e HBM at 5k
# nodes for the PLAIN kernel; the constraint-carrying variant self-caps
# (ops/backend.py full_batch_cap) and chunks
BATCH = int(os.environ.get("BENCH_BATCH", "16384"))
# depth-2 batch pipeline: with async D2H result copies the second
# in-flight batch hides the host tail behind the device flight
DEPTH = int(os.environ.get("BENCH_DEPTH", "2"))

EXTRA_CONFIGS = {
    # p99 under steady paced arrival — the honest latency numbers; the
    # headline's p99 is backlog drain time.  Latency mode: deep micro-
    # batch pipeline + ~1ms admission window (scheduler.py
    # pipeline_depth/admission_interval).  The ~100ms pipeline-flight
    # floor on these numbers is the tunneled chip's fixed per-transfer
    # latency (see LATENCY.md for the measured curve and the
    # direct-attached projection).
    "SchedulingBasicPaced": {"workload": "SchedulingBasicLarge",
                             "nodes": 5000, "pods": 24_000, "batch": 512,
                             "rate": 8000, "timeout": 900.0,
                             "depth": 12, "admission_ms": 1.0},
    "SchedulingBasicPaced4k": {"workload": "SchedulingBasicLarge",
                               "nodes": 5000, "pods": 12_000, "batch": 512,
                               "rate": 4000, "timeout": 900.0,
                               "depth": 12, "admission_ms": 1.0},
    "SchedulingBasicPaced1k": {"two_pass": True,
                         "workload": "SchedulingBasicLarge",
                               "nodes": 5000, "pods": 6_000, "batch": 256,
                               "rate": 1000, "timeout": 900.0,
                               "depth": 12, "admission_ms": 1.0},
    # single pass despite the tier's 10-17k weather band: a second
    # 100k pass costs up to ~25 min in bad weather and the driver's
    # bench budget is finite — the band is documented in README/LATENCY.
    # Explicitly single-chip: the direct A/B against the sharded
    # Scheduling100k HEADLINE row (main() head_cfg)
    "Scheduling100k": {"workload": "SchedulingBasicLarge",
                       "nodes": 100_000, "pods": 200_000, "batch": 16384,
                       "depth": 2, "timeout": 1200.0, "backend": "tpu"},
    # constraint workloads: batch 8192 (full_cap chunks pipeline inside
    # ONE dispatch -> fewer fixed per-call tunnel round trips) + a 50ms
    # admission window so an arrival flood coalesces into ~2 dispatches
    "SchedulingPodAntiAffinity": {"two_pass": True,
                         "workload": "SchedulingPodAntiAffinity",
                                  "batch": 8192, "depth": 2,
                                  "admission_ms": 50.0,
                                  "timeout": 900.0},
    # 2000 DISTINCT per-service anti-affinity selectors through a few
    # dozen hash-shared tensor slots (flatten.GroupBucket); the result's
    # escape_rate reports the escaped-to-oracle fraction (target <5%)
    "SchedulingHighCardinality": {"two_pass": True,
                         "workload": "SchedulingHighCardinality",
                                  "batch": 8192, "depth": 2,
                                  "admission_ms": 50.0,
                                  "timeout": 900.0},
    "TopologySpreading": {"two_pass": True,
                         "workload": "TopologySpreading", "batch": 8192,
                          "depth": 2, "admission_ms": 50.0,
                          "timeout": 900.0},
    "CoschedulingGang": {"two_pass": True,
                         "workload": "CoschedulingGang", "batch": 8192,
                         "depth": 2, "admission_ms": 50.0,
                         "timeout": 900.0},
    # the front door: same workload THROUGH a real apiserver with RBAC
    # + admission + WAL, every component speaking HTTP (the reference
    # harness schedules via a real apiserver, util.go:79-108).  The
    # gap vs the LocalClient headline quantifies the REST tax.
    "SchedulingBasicHTTP": {"workload": "SchedulingBasicLarge",
                            "nodes": 5000, "pods": 10_000, "batch": 4096,
                            "depth": 2, "timeout": 900.0, "http": True},
    # the front door with the apiserver as a SEPARATE PROCESS — the
    # reference's actual deployment shape (separate binaries, no shared
    # GIL between server and scheduler)
    "SchedulingBasicHTTPProc": {"workload": "SchedulingBasicLarge",
                                "nodes": 5000, "pods": 10_000,
                                "batch": 4096, "depth": 2,
                                "timeout": 900.0, "http": "proc"},
    # the device-worker seam cost: identical plain batches through the
    # in-process backend vs through a gRPC DeviceWorker (ops/remote.py)
    # in steady state — quantifies what crossing the north star's shim
    # costs per step
    "RemoteSeamGrpc": {"seam": "grpc", "timeout": 600.0},
    # the same seam under seeded chaos (ops/faults.py): drops, delays,
    # corrupt frames, one worker kill+restart and a scripted outage that
    # trips the circuit breaker into the in-process rung and back
    # (ops/failover.py).  Measures what the retry/resync/failover
    # machinery costs relative to RemoteSeamGrpc's clean run; the
    # acceptance bound is within 2x of clean
    "RemoteSeamFaulty": {"seam": "grpc", "faulty": True, "timeout": 900.0},
    # the HOST CEILING: the identical pipeline with the device step
    # nulled (ops/nullbackend.py) — every pod/s here is host work, so
    # this row tracks the single-interpreter wall (VERDICT r4 #1) and
    # any native/multi-process host improvement in isolation from
    # tunnel weather.  No chip involved; tunnel drift cannot touch it.
    "SchedulingHostNull": {"workload": "SchedulingBasicLarge",
                           "nodes": 5000, "pods": 50_000, "batch": 16384,
                           "depth": 1, "timeout": 900.0, "null": True},
    # ---- round-5 workload breadth (each is an existing code path that
    # had no number attached; reference performance-config.yaml:52-598).
    # Configs run at their YAML-configured reference scales.
    "PreemptionBasic": {"two_pass": True,
                        "workload": "PreemptionBasic", "batch": 1024,
                        "depth": 1, "timeout": 900.0},
    # victim-tensor stress: 8 residents/node, multi-victim evictions, 4
    # preemptors contending per node (batched DryRunPreemption + bulk
    # commit; the conflict-resolution waves are the measured path)
    "PreemptionDense": {"two_pass": True,
                        "workload": "PreemptionDense", "batch": 1024,
                        "depth": 1, "timeout": 900.0},
    "Unschedulable": {"workload": "Unschedulable", "batch": 4096,
                      "depth": 2, "timeout": 900.0},
    "SchedulingWithMixedChurn": {"workload": "SchedulingWithMixedChurn",
                                 "batch": 4096, "depth": 2,
                                 "timeout": 900.0},
    "SchedulingPodAffinity": {"workload": "SchedulingPodAffinity",
                              "batch": 8192, "depth": 2,
                              "admission_ms": 50.0, "timeout": 900.0},
    "SchedulingNodeAffinity": {"workload": "SchedulingNodeAffinity",
                               "batch": 4096, "depth": 2,
                               "timeout": 900.0},
    "SchedulingPreferredPodAffinity": {
        "workload": "SchedulingPreferredPodAffinity",
        "batch": 8192, "depth": 2, "admission_ms": 50.0,
        "timeout": 900.0},
    "SchedulingPreferredPodAntiAffinity": {
        "workload": "SchedulingPreferredPodAntiAffinity",
        "batch": 8192, "depth": 2, "admission_ms": 50.0,
        "timeout": 900.0},
    "PreferredTopologySpreading": {
        "workload": "PreferredTopologySpreading",
        "batch": 8192, "depth": 2, "admission_ms": 50.0,
        "timeout": 900.0},
    "MixedSchedulingBasePod": {"workload": "MixedSchedulingBasePod",
                               "batch": 4096, "depth": 2,
                               "timeout": 900.0},
    "SchedulingSecrets": {"workload": "SchedulingSecrets", "batch": 4096,
                          "depth": 2, "timeout": 900.0},
    # namespaceSelector terms are tensor-encoded: the flattener resolves
    # each term against its informer-fed namespace-label cache into a
    # concrete namespace set at encode time, so these run the device
    # regime at escape_rate 0.0 (reference :492-598)
    # NOTE: no pct_nodes on the required-anti row — one-pod-per-host
    # anti-affinity is feasibility-SEEKING at the contended tail, and a
    # 2% sample often contains zero free hosts (measured: the run
    # parked/retried its way past the timeout); the adaptive default
    # finds them
    "SchedulingRequiredPodAntiAffinityWithNSSelector": {
        "workload": "SchedulingRequiredPodAntiAffinityWithNSSelector",
        "batch": 4096, "depth": 2, "timeout": 1200.0},
    "SchedulingPreferredAffinityWithNSSelector": {
        "workload": "SchedulingPreferredAffinityWithNSSelector",
        "batch": 4096, "depth": 2, "timeout": 900.0, "pct_nodes": 2},
    # the stress shape for namespace resolution: 201 namespaces in the
    # vocab, every term fanning out across all of them, required-anti
    # AND preferred-affinity on the same pods
    "SchedulingNSSelectorDense": {
        "workload": "SchedulingNSSelectorDense",
        "batch": 4096, "depth": 2, "timeout": 1200.0},
    # blended tensor+oracle: 5% Gt node-affinity escapes; the config
    # whose escape_rate must be NON-zero (honest coverage)
    # pct_nodes=2: percentageOfNodesToScore for the ESCAPED pods'
    # per-pod cycles (the reference's sampling knob; its adaptive
    # default would score ~500 nodes per oracle pod and the blended
    # number would measure Python scoring, not the mixed regime)
    "SchedulingMixedEscapes": {"workload": "SchedulingMixedEscapes",
                               "batch": 16384, "depth": 2,
                               "timeout": 900.0, "pct_nodes": 2},
    # overload acceptance row: a 30k-pod flood with a periodic escape
    # class, under a seeded ChaosBatchBackend storm schedule, with the
    # full overload policy active (bounded admission + AIMD waves +
    # escape breaker).  The detail carries shed/deferred/wave counters;
    # bench.py --overload runs the same shape A/B with the policy off.
    "SchedulingOverloadFlood": {"workload": "SchedulingOverloadFlood",
                                "batch": 4096, "depth": 2,
                                "timeout": 1200.0, "overload": True},
}


def _overload_shape(batch: int):
    """The shared --overload/SchedulingOverloadFlood knobs: a policy
    sized against the flood (cap = a few waves of backlog) and a seeded
    chaos schedule (slow waves + adversarial all-escape waves).  One
    place so the suite row and the A/B mode measure the same regime."""
    from kubernetes_tpu.ops.faults import OverloadSchedule
    from kubernetes_tpu.scheduler.config import OverloadPolicy

    policy = OverloadPolicy(
        # the chaos arm pins always-on: the A/B measures the PROTECTION
        # LAYERS against the storm, not the engagement controller's
        # detection latency (the healthy arm measures that)
        engagement="always",
        queue_cap=int(os.environ.get("BENCH_OVERLOAD_CAP", str(4 * batch))),
        shed_protect_priority=1000,   # the workload's hipri- pods
        shed_protect_age=30.0,
        slo_p99_ms=250.0,
        wave_min=max(16, batch // 64),
        wave_increase=max(32, batch // 32),
        escape_rate_threshold=0.5,
        escape_min_batch=64,
        breaker_threshold=1,
        breaker_probe_interval=0.5,
        # generous: the watchdog is for WEDGED waves, not a loaded host
        wave_deadline=120.0)
    chaos = OverloadSchedule(seed=42, slow_rate=0.05, slow_s=0.05,
                             all_escape_rate=0.1)
    return policy, chaos


def run_seam_micro(kind: str = "grpc", faulty: bool = False) -> dict:
    """Steady-state assign() through the in-process backend vs the same
    batches through a DeviceWorker seam; returns pods/s both ways.

    faulty=True drives the seam through ops/faults.py chaos (seeded
    drops/delays/corrupt frames, one worker kill+restart, and a scripted
    outage long enough to trip the ops/failover.py circuit breaker into
    the in-process rung and probe back) — the throughput cost of the
    resilience machinery, plus its counters."""
    import time as _t

    from kubernetes_tpu.ops.backend import TPUBatchBackend
    from kubernetes_tpu.ops.flatten import Caps
    from kubernetes_tpu.ops.remote import (
        DeviceWorker, GrpcDeviceWorker, RemoteTPUBatchBackend,
    )
    from kubernetes_tpu.scheduler.cache import Cache, Snapshot
    from kubernetes_tpu.scheduler.types import PodInfo
    from kubernetes_tpu.testing import make_node, make_pod

    n_nodes = int(os.environ.get("BENCH_SEAM_NODES", "5000"))
    caps = Caps(n_cap=max(1024, -(-int(n_nodes * 1.1) // 256) * 256),
                l_cap=128, kl_cap=62, t_cap=16, pt_cap=16,
                s_cap=3, sg_cap=16, asg_cap=16)
    BATCH = int(os.environ.get("BENCH_SEAM_BATCH", "4096"))
    ROUNDS = 6
    cache = Cache()
    for i in range(n_nodes):
        cache.add_node(make_node(f"n{i}")
                       .capacity(cpu="64", mem="256Gi", pods=1000).build())
    snap = cache.update_snapshot(Snapshot())

    def drive(backend, tag):
        backend.warmup()
        batches = [[PodInfo(make_pod(f"{tag}{r}-{i}")
                            .req(cpu="10m", mem="16Mi").build())
                    for i in range(BATCH)] for r in range(ROUNDS)]
        backend.assign(batches[0], snap)  # warm round
        t0 = _t.monotonic()
        placed = 0
        for r in range(1, ROUNDS):
            placed += sum(1 for nm, _ in backend.assign(batches[r], snap)
                          if nm)
        rate = (ROUNDS - 1) * BATCH / (_t.monotonic() - t0)
        return placed, rate

    worker = (GrpcDeviceWorker() if kind == "grpc"
              else DeviceWorker()).start()
    detail: dict = {}
    try:
        if faulty:
            from kubernetes_tpu.ops.failover import FailoverBatchBackend
            from kubernetes_tpu.ops.faults import (
                KILL, NONE, FaultSchedule, FaultyTransport,
            )
            from kubernetes_tpu.ops.remote import transport_for
            from kubernetes_tpu.scheduler.config import RemoteSeamPolicy
            from kubernetes_tpu.scheduler.scheduler import (
                BackendUnavailableError,
            )

            class _BenchFaultSchedule(FaultSchedule):
                """Seeded weather + one kill at the 4th step + one hard
                outage (every call dropped) after the 12th step, long
                enough to exhaust retries twice and open the breaker."""

                def __init__(self):
                    super().__init__(seed=42, drop_rate=0.02,
                                     delay_rate=0.05, corrupt_rate=0.02,
                                     delay_s=0.005)
                    self.steps = 0
                    self.killed = False
                    self.outage_from: int | None = None
                    # exactly (max_retries+1) * failure_threshold calls:
                    # enough to open the breaker, gone by the first probe
                    self.outage_calls = 4

                def action(self, i, verb):
                    if verb.startswith("/step"):
                        self.steps += 1
                        # the kill lands on the (untimed) warm round: the
                        # resync's worker-side recompile is a fixed restart
                        # cost, not steady-state chaos throughput
                        if self.steps == 3 and not self.killed:
                            self.killed = True
                            self.rng.random()
                            return KILL
                        if self.steps == 8 and self.outage_from is None:
                            self.outage_from = i
                    if (self.outage_from is not None
                            and i < self.outage_from + self.outage_calls):
                        self.rng.random()
                        return "drop"
                    return super().action(i, verb)

            schedule = _BenchFaultSchedule()
            transport = FaultyTransport(transport_for(worker.url), schedule,
                                        on_kill=worker.simulate_restart)
            policy = RemoteSeamPolicy(max_retries=1, retry_base=0.01,
                                      retry_max=0.05, probe_interval=0.2)
            remote = RemoteTPUBatchBackend(worker.url, caps,
                                           batch_size=BATCH,
                                           transport=transport,
                                           policy=policy)
            ladder = FailoverBatchBackend(
                [("remote", remote),
                 ("inproc", TPUBatchBackend(caps, batch_size=BATCH))],
                failure_threshold=2, probe_interval=0.2)
            requeues = 0

            def drive_faulty(backend, tag):
                nonlocal requeues
                backend.warmup()
                batches = [[PodInfo(make_pod(f"{tag}{r}-{i}")
                                    .req(cpu="10m", mem="16Mi").build())
                            for i in range(BATCH)] for r in range(ROUNDS)]

                def assign_retry(batch):
                    # the scheduler's requeue loop in miniature: a failed
                    # batch re-enters with backoff until a rung serves it
                    nonlocal requeues
                    for _ in range(20):
                        try:
                            return backend.assign(batch, snap)
                        except BackendUnavailableError:
                            requeues += 1
                            _t.sleep(0.02)
                    raise RuntimeError("bench: batch never recovered")

                assign_retry(batches[0])  # warm round
                t0 = _t.monotonic()
                placed = 0
                for r in range(1, ROUNDS):
                    placed += sum(1 for nm, _ in assign_retry(batches[r])
                                  if nm)
                rate = (ROUNDS - 1) * BATCH / (_t.monotonic() - t0)
                # recovery rounds (untimed): wait out probe windows until
                # the breaker half-opens, health-probes the recovered
                # worker and FAILS BACK before counters are reported (a
                # weather-dropped probe just re-arms the window)
                for n in range(5):
                    _t.sleep(policy.probe_interval + 0.05)
                    assign_retry([PodInfo(make_pod(f"{tag}rec{n}-{i}")
                                          .req(cpu="10m",
                                               mem="16Mi").build())
                                  for i in range(64)])
                    if backend.breaker_state().get("remote") == 0.0:
                        break
                return placed, rate

            _, remote_rate = drive_faulty(ladder, "r")
            detail = {"failover": ladder.seam_snapshot(),
                      "breakers": ladder.breaker_state(),
                      "injected": dict(transport.injected),
                      "bench_requeues": requeues}
        else:
            _, remote_rate = drive(
                RemoteTPUBatchBackend(worker.url, caps, batch_size=BATCH),
                "r")
    finally:
        worker.stop()
    _, local_rate = drive(TPUBatchBackend(caps, batch_size=BATCH), "l")
    return {"seam": kind + ("_faulty" if faulty else ""),
            "inproc_pods_per_s": round(local_rate, 1),
            "remote_pods_per_s": round(remote_rate, 1),
            "seam_cost_ratio": round(local_rate / max(remote_rate, 1e-9),
                                     2), **detail}


def run_trace(out_path: str | None = None) -> dict:
    """--trace mode: a 5k-pod SchedulingBasicLarge pass over the gRPC
    DeviceWorker seam with full head-sampling, written as Chrome
    trace-event JSON (chrome://tracing / Perfetto), then the identical
    pass untraced to report the overhead honestly.

    The export carries both sides of the seam: scheduler-process spans
    (schedule_batch > queue.pop / snapshot.flatten / plugin.* / tpu.* /
    bind) and worker-process spans (worker./step...) parented into the
    same traces via the propagated traceparent."""
    import copy

    from kubernetes_tpu.component_base import tracing
    from kubernetes_tpu.perf import (
        caps_for_nodes, load_workloads, run_named_workload,
    )
    from kubernetes_tpu.perf.scheduler_perf import is_measured

    nodes = int(os.environ.get("BENCH_TRACE_NODES", "1000"))
    pods = int(os.environ.get("BENCH_TRACE_PODS", "5000"))
    batch = int(os.environ.get("BENCH_TRACE_BATCH", "1024"))
    out_path = out_path or os.environ.get(
        "BENCH_TRACE_OUT", "trace_SchedulingBasicLarge.json")

    def build_cfg() -> dict:
        cfg = copy.deepcopy(load_workloads()["SchedulingBasicLarge"])
        tpl = cfg["workloadTemplate"]
        for op in tpl:
            if op["opcode"] == "createNodes":
                op["count"] = nodes
            elif op["opcode"] == "createPods" and is_measured(op, tpl):
                op["count"] = pods
            elif op["opcode"] == "barrier":
                op["timeout"] = 600.0
        return cfg

    caps = caps_for_nodes(nodes)
    provider = tracing.TracerProvider(sampling_rate_per_million=1_000_000,
                                      max_spans=65536, max_traces=8192)
    summary_t, stats_t = run_named_workload(
        build_cfg(), tpu=True, caps=caps, batch_size=batch,
        pipeline_depth=2, remote_seam="grpc", tracing_provider=provider)
    spans = provider.snapshot() + list(stats_t.get("worker_spans") or ())
    with open(out_path, "w") as f:
        json.dump(tracing.to_chrome_trace(spans), f)
    summary_u, _ = run_named_workload(
        build_cfg(), tpu=True, caps=caps, batch_size=batch,
        pipeline_depth=2, remote_seam="grpc")
    span_names: dict[str, int] = {}
    for s in spans:
        span_names[s.name] = span_names.get(s.name, 0) + 1
    worker_parented = sum(1 for s in spans
                          if s.name.startswith("worker.")
                          and s.parent_span_id is not None)
    traced = summary_t.average
    untraced = summary_u.average
    return {
        "nodes": nodes, "pods": pods, "batch": batch,
        "trace_file": os.path.abspath(out_path),
        "events": len(spans),
        "span_names": dict(sorted(span_names.items())),
        "worker_spans_parented": worker_parented,
        "traced_pods_per_s": round(traced, 1),
        "untraced_pods_per_s": round(untraced, 1),
        "overhead_ratio": round(untraced / max(traced, 1e-9), 3),
        "barrier_ok": stats_t.get("barrier_ok", False),
    }


def run_profile(out_path: str | None = None) -> dict:
    """--profile mode: the continuous-performance-observatory read-out.

    One SchedulingBasicLarge pass with the full `profiling:` stanza on
    (always-on host sampler + device cost census + SLO tracker), then
    the identical pass with everything off to report the sampling
    overhead honestly (the observatory is only deployable always-on if
    this ratio stays within noise).  Writes the PROFILE artifact: per
    bench row, the per-stage host-time attribution, the device census
    (collective bytes per wave/step, flops, HBM bytes) and the SLO
    quantiles + burn rates, plus the collapsed stacks for flamegraphs."""
    import copy

    from kubernetes_tpu.component_base import profiling as cbp
    from kubernetes_tpu.perf import (
        caps_for_nodes, load_workloads, run_named_workload,
    )
    from kubernetes_tpu.perf.scheduler_perf import is_measured
    from kubernetes_tpu.scheduler.config import ProfilingPolicy

    nodes = int(os.environ.get("BENCH_PROFILE_NODES", "1000"))
    pods = int(os.environ.get("BENCH_PROFILE_PODS", "5000"))
    batch = int(os.environ.get("BENCH_PROFILE_BATCH", "1024"))
    out_path = out_path or os.environ.get(
        "BENCH_PROFILE_OUT", "profile_SchedulingBasicLarge.json")

    def build_cfg() -> dict:
        cfg = copy.deepcopy(load_workloads()["SchedulingBasicLarge"])
        tpl = cfg["workloadTemplate"]
        for op in tpl:
            if op["opcode"] == "createNodes":
                op["count"] = nodes
            elif op["opcode"] == "createPods" and is_measured(op, tpl):
                op["count"] = pods
            elif op["opcode"] == "barrier":
                op["timeout"] = 600.0
        return cfg

    caps = caps_for_nodes(nodes)
    policy = ProfilingPolicy(enabled=True, census=True)
    summary_p, stats_p = run_named_workload(
        build_cfg(), tpu=True, caps=caps, batch_size=batch,
        pipeline_depth=2, profiling_policy=policy)
    collapsed = cbp.default_host_profiler.collapsed()
    summary_u, _ = run_named_workload(
        build_cfg(), tpu=True, caps=caps, batch_size=batch,
        pipeline_depth=2)

    census = stats_p.get("device_census") or {}
    census_summary: dict[str, dict] = {}
    for label, rec in census.items():
        per_wave, per_call = cbp.collective_bytes_by_op(rec)
        census_summary[label] = {
            "per_wave_bytes": rec.get("per_wave_bytes", 0),
            "per_call_bytes": rec.get("per_call_bytes", 0),
            "wave_collective_bytes": per_wave,
            "step_collective_bytes": per_call,
            **(rec.get("cost") or {}),
        }
    e2e = stats_p.get("e2e") or {}
    row = {
        "nodes": nodes, "pods": pods, "batch": batch,
        "pods_per_s": round(summary_p.average, 1),
        "p50_ms": e2e.get("p50_ms"), "p95_ms": e2e.get("p95_ms"),
        "p99_ms": e2e.get("p99_ms"),
        "host_stages": stats_p.get("host_stages"),
        "profile_samples": stats_p.get("profile_samples"),
        "slo": stats_p.get("slo"),
        "census": census_summary,
    }
    with open(out_path, "w") as f:
        json.dump({"rows": [row], "device_census": census,
                   "hot_stacks": stats_p.get("hot_stacks"),
                   "collapsed_stacks": collapsed}, f, indent=1)

    profiled = summary_p.average
    unprofiled = summary_u.average
    return {
        **row,
        "profile_file": os.path.abspath(out_path),
        "profiled_pods_per_s": round(profiled, 1),
        "unprofiled_pods_per_s": round(unprofiled, 1),
        "overhead_ratio": round(unprofiled / max(profiled, 1e-9), 3),
        "barrier_ok": stats_p.get("barrier_ok", False),
    }


def run_timeline(out_path: str | None = None) -> dict:
    """--timeline mode: the wave-timeline observatory read-out.

    One SchedulingBasicLarge pass with `profiling.timeline` armed (the
    interval ring + per-pod decomposition), then the identical pass with
    it off to pin the recording overhead honestly (the acceptance bar is
    ≤5%, enforced by tests/test_timeline.py on a null-device workload).
    Writes the TIMELINE artifact: the Perfetto-loadable Chrome trace of
    the ring plus the summary (union-derived device idle share, per-stage
    overlap ratios, per-segment latency quantiles)."""
    import copy

    from kubernetes_tpu.component_base import timeline as cb_timeline
    from kubernetes_tpu.perf import (
        caps_for_nodes, load_workloads, run_named_workload,
    )
    from kubernetes_tpu.perf.scheduler_perf import is_measured
    from kubernetes_tpu.scheduler.config import ProfilingPolicy

    nodes = int(os.environ.get("BENCH_TIMELINE_NODES", "1000"))
    pods = int(os.environ.get("BENCH_TIMELINE_PODS", "5000"))
    batch = int(os.environ.get("BENCH_TIMELINE_BATCH", "1024"))
    out_path = out_path or os.environ.get(
        "BENCH_TIMELINE_OUT", "timeline_SchedulingBasicLarge.json")

    def build_cfg() -> dict:
        cfg = copy.deepcopy(load_workloads()["SchedulingBasicLarge"])
        tpl = cfg["workloadTemplate"]
        for op in tpl:
            if op["opcode"] == "createNodes":
                op["count"] = nodes
            elif op["opcode"] == "createPods" and is_measured(op, tpl):
                op["count"] = pods
            elif op["opcode"] == "barrier":
                op["timeout"] = 600.0
        return cfg

    caps = caps_for_nodes(nodes)
    policy = ProfilingPolicy(timeline=True)
    summary_t, stats_t = run_named_workload(
        build_cfg(), tpu=True, caps=caps, batch_size=batch,
        pipeline_depth=2, profiling_policy=policy)
    # snapshot the trace BEFORE the off-side pass disarms the ring
    trace_doc = cb_timeline.default_timeline.to_chrome_trace()
    summary_u, _ = run_named_workload(
        build_cfg(), tpu=True, caps=caps, batch_size=batch,
        pipeline_depth=2)

    tl = stats_t.get("timeline") or {}
    e2e = stats_t.get("e2e") or {}
    row = {
        "nodes": nodes, "pods": pods, "batch": batch,
        "pods_per_s": round(summary_t.average, 1),
        "p50_ms": e2e.get("p50_ms"), "p95_ms": e2e.get("p95_ms"),
        "p99_ms": e2e.get("p99_ms"),
        "device_idle_share": tl.get("device_idle_share"),
        "stage_overlap": tl.get("overlap"),
        "latency_decomposition": tl.get("segments"),
        "timeline_intervals": tl.get("intervals"),
        "pods_decomposed": tl.get("pods_decomposed"),
    }
    with open(out_path, "w") as f:
        json.dump({"rows": [row], "chrome_trace": trace_doc}, f, indent=1)

    timed = summary_t.average
    untimed = summary_u.average
    return {
        **row,
        "timeline_file": os.path.abspath(out_path),
        "timed_pods_per_s": round(timed, 1),
        "untimed_pods_per_s": round(untimed, 1),
        "overhead_ratio": round(untimed / max(timed, 1e-9), 3),
        "barrier_ok": stats_t.get("barrier_ok", False),
    }


def run_pipeline_ab() -> dict:
    """--pipeline-ab mode: the wave-pipeline acceptance A/B.

    The identical SchedulingBasicLarge pass at depth 1 (serial wave
    loop, the bit-parity baseline arm — tests/test_churn_parity.py pins
    that both depths produce identical assignments) and depth 2 (the
    double-buffered pipeline: host drain/patch/form/h2d of wave N+1
    overlaps wave N's device step, binds absorbed by the binder
    worker), both with the timeline ring armed so each arm carries its
    union-derived device_idle_share and per-stage overlap.  In-process
    by design (same trade as --timeline): one warmed interpreter +
    device for both arms.  The acceptance bars ride the small tier,
    where round 15 measured the device idle 22.8% of the wall:
    depth-2 idle share < 0.20 and throughput ≥ 1.3x the depth-1 arm."""
    import copy

    from kubernetes_tpu.perf import (
        caps_for_nodes, load_workloads, run_named_workload,
    )
    from kubernetes_tpu.perf.scheduler_perf import is_measured
    from kubernetes_tpu.scheduler.config import ProfilingPolicy

    nodes = int(os.environ.get("BENCH_PIPELINE_NODES", "1000"))
    pods = int(os.environ.get("BENCH_PIPELINE_PODS", "5000"))
    batch = int(os.environ.get("BENCH_PIPELINE_BATCH", "1024"))
    # Off-host flight arm (ops/nullbackend.FlightDelayBackend): pins
    # every wave's device flight to this wall duration at ~zero host
    # CPU, the shape a real accelerator presents.  On a single-core box
    # the CPU-simulated device shares the core with the host, so the
    # depth-2 overlap is physically impossible to measure without it —
    # 0 keeps the plain CPU-sim arms.
    flight_ms = float(os.environ.get("BENCH_PIPELINE_FLIGHT_MS", "0"))

    def build_cfg() -> dict:
        cfg = copy.deepcopy(load_workloads()["SchedulingBasicLarge"])
        tpl = cfg["workloadTemplate"]
        for op in tpl:
            if op["opcode"] == "createNodes":
                op["count"] = nodes
            elif op["opcode"] == "createPods" and is_measured(op, tpl):
                op["count"] = pods
            elif op["opcode"] == "barrier":
                op["timeout"] = scaled_timeout(pods, 600.0)
        return cfg

    caps = caps_for_nodes(nodes)
    out: dict = {"nodes": nodes, "pods": pods, "batch": batch,
                 "flight_ms": flight_ms}
    # depth-2 warm pass (untimed): both arms then run against a warmed
    # interpreter/jit cache, so the A/B isn't depth-2-pays-compile
    run_named_workload(build_cfg(), tpu=True, caps=caps, batch_size=batch,
                       pipeline_depth=2)
    for tag, depth in (("depth1", 1), ("depth2", 2)):
        summary, stats = run_named_workload(
            build_cfg(), tpu=True, caps=caps, batch_size=batch,
            pipeline_depth=depth,
            device_flight_s=flight_ms / 1000.0,
            profiling_policy=ProfilingPolicy(timeline=True))
        tl = stats.get("timeline") or {}
        e2e = stats.get("e2e") or {}
        out[tag] = {
            "pods_per_s": round(summary.average, 1),
            "p50_ms": e2e.get("p50_ms"), "p95_ms": e2e.get("p95_ms"),
            "p99_ms": e2e.get("p99_ms"),
            "device_idle_share": tl.get("device_idle_share"),
            "stage_overlap": tl.get("overlap"),
            "barrier_ok": stats.get("barrier_ok", False),
        }
    d1, d2 = out["depth1"], out["depth2"]
    out["speedup"] = round(
        d2["pods_per_s"] / max(d1["pods_per_s"], 1e-9), 3)
    return out


def run_overload() -> dict:
    """--overload mode: the SchedulingOverloadFlood workload under the
    seeded chaos schedule, A/B WITH the overload policy (bounded
    admission + AIMD waves + escape-storm breaker + watchdog) and
    WITHOUT it.  The without side sends every injected escape storm to
    the per-pod oracle and admits the whole flood unbounded — the gap
    in pods/s, p99 and peak queue depth is what the protections buy.
    Two passes in one process (same trade as --trace: a shared
    interpreter beats doubling the device warmup)."""
    import copy

    from kubernetes_tpu.perf import (
        caps_for_nodes, load_workloads, run_named_workload,
    )
    from kubernetes_tpu.perf.scheduler_perf import is_measured

    nodes = int(os.environ.get("BENCH_OVERLOAD_NODES", "1000"))
    pods = int(os.environ.get("BENCH_OVERLOAD_PODS", "10000"))
    batch = int(os.environ.get("BENCH_OVERLOAD_BATCH", "2048"))

    def build_cfg() -> dict:
        cfg = copy.deepcopy(load_workloads()["SchedulingOverloadFlood"])
        tpl = cfg["workloadTemplate"]
        for op in tpl:
            if op["opcode"] == "createNodes":
                op["count"] = nodes
            elif op["opcode"] == "createPods" and is_measured(op, tpl):
                op["count"] = pods
            elif op["opcode"] == "barrier":
                op["timeout"] = 900.0
        return cfg

    caps = caps_for_nodes(nodes)
    out: dict = {"nodes": nodes, "pods": pods, "batch": batch}
    for tag, with_policy in (("with_policy", True), ("without_policy", False)):
        policy, chaos = _overload_shape(batch)
        summary, stats = run_named_workload(
            build_cfg(), tpu=True, caps=caps, batch_size=batch,
            pipeline_depth=2, overload=policy if with_policy else None,
            chaos_schedule=chaos)
        e2e = stats.get("e2e") or {}
        side = {"pods_per_s": round(summary.average, 1),
                "p50_ms": e2e.get("p50_ms"),
                "p95_ms": e2e.get("p95_ms"),
                "p99_ms": e2e.get("p99_ms"),
                "barrier_ok": stats.get("barrier_ok", False),
                "chaos_injected": stats.get("chaos_injected")}
        if "escape_rate" in stats:
            side["escape_rate"] = stats["escape_rate"]
        if "overload" in stats:
            side["overload"] = stats["overload"]
        out[tag] = side
    wp, np_ = out["with_policy"], out["without_policy"]
    out["policy_speedup"] = round(
        wp["pods_per_s"] / max(np_["pods_per_s"], 1e-9), 2)
    # healthy-box parity: the SAME flood, NO chaos, the DEFAULT policy
    # (auto engagement) vs no policy at all.  This is the on-by-default
    # headline — a disengaged controller must cost nothing measurable,
    # so healthy_parity should sit within a few percent of 1.0.  Both
    # shapes get an untimed warmup pass first (the --trace/--timeline
    # A/B discipline): the chaos arms above leave allocator/JIT state
    # that otherwise lands entirely on whichever healthy arm runs first
    # and read as a ~3x phantom gap.
    from kubernetes_tpu.scheduler.config import OverloadPolicy
    for pol in (None, OverloadPolicy()):
        run_named_workload(build_cfg(), tpu=True, caps=caps,
                           batch_size=batch, pipeline_depth=2,
                           overload=pol)
    for tag, pol in (("healthy_default", OverloadPolicy()),
                     ("healthy_no_policy", None)):
        summary, stats = run_named_workload(
            build_cfg(), tpu=True, caps=caps, batch_size=batch,
            pipeline_depth=2, overload=pol)
        e2e = stats.get("e2e") or {}
        side = {"pods_per_s": round(summary.average, 1),
                "p50_ms": e2e.get("p50_ms"),
                "p99_ms": e2e.get("p99_ms"),
                "barrier_ok": stats.get("barrier_ok", False)}
        if "overload" in stats:
            side["engagement"] = stats["overload"].get("engagement")
            side["transitions"] = stats["overload"].get("transitions")
        out[tag] = side
    out["healthy_parity"] = round(
        out["healthy_default"]["pods_per_s"]
        / max(out["healthy_no_policy"]["pods_per_s"], 1e-9), 3)
    return out


def warm_ab_child_main(mode: str) -> None:
    """One restart path of the --warm-ab A/B, run in a FRESH interpreter
    (dispatched by run_warm_ab via _BENCH_WARM_AB_CHILD).  A restarted
    scheduler is a fresh process, and measuring restart work inside the
    warmed bench parent is wrong by ~10x: the parent's fragmented
    allocator arenas (store + caches + backends all live) slow the
    millions of small allocations a checkpoint load or cache prime makes
    — measured 0.6s vs 5.7s for the same 200k-object unpickle.  Timing
    inside the child, after imports, keeps interpreter+JIT start out of
    the ratio (neither side's number includes it)."""
    from kubernetes_tpu.client.clientset import NODES, PODS
    from kubernetes_tpu.client.http_client import HTTPClient
    from kubernetes_tpu.ops.backend import TPUBatchBackend
    from kubernetes_tpu.perf import caps_for_nodes
    from kubernetes_tpu.scheduler.cache import Cache

    n_nodes = int(os.environ["BENCH_AB_NODES"])
    batch = int(os.environ["BENCH_AB_BATCH"])
    caps = caps_for_nodes(n_nodes)
    out: dict = {}
    if mode == "cold":
        # cold restart: wire LIST + cache prime + full flatten encode
        http = HTTPClient.from_url(os.environ["BENCH_AB_URL"])
        t0 = time.monotonic()
        nodes, _ = http.list(NODES)
        pods, _ = http.list(PODS)
        out["wire_list_s"] = round(time.monotonic() - t0, 3)
        t0 = time.monotonic()
        cache = Cache()
        for o in nodes:
            cache.add_node(o)
        for p in pods:
            cache.add_pod(p)
        out["cache_prime_s"] = round(time.monotonic() - t0, 3)
        backend = TPUBatchBackend(caps, batch_size=batch)
        t0 = time.monotonic()
        backend.tensors.update_from_snapshot_tracked(cache.flatten_view())
        out["full_encode_s"] = round(time.monotonic() - t0, 3)
    else:
        # warm restart: checkpoint load + cache prime from its objects +
        # digest-adoption sweep (no wire traffic at all)
        backend = TPUBatchBackend(caps, batch_size=batch)
        t0 = time.monotonic()
        warm = backend.warm_start(os.environ["BENCH_AB_CKPT"])
        out["load_s"] = round(time.monotonic() - t0, 3)
        t0 = time.monotonic()
        cache = Cache()
        for o in warm["objects"][NODES]:
            cache.add_node(o)
        for p in warm["objects"][PODS]:
            cache.add_pod(p)
        out["cache_prime_s"] = round(time.monotonic() - t0, 3)
        t0 = time.monotonic()
        dropped = backend.warm_align(cache.flatten_view())
        out["adopt_sweep_s"] = round(time.monotonic() - t0, 3)
        adopted = backend.stats.get("warm_adopted", 0)
        assert dropped == 0 and adopted == n_nodes, \
            f"warm adoption incomplete: {adopted}/{n_nodes} " \
            f"({dropped} dropped)"
        out["adopted"] = adopted
    out["total_s"] = round(sum(v for k, v in out.items()
                               if k.endswith("_s")), 3)
    print(json.dumps(out), flush=True)


def run_warm_ab() -> dict:
    """--warm-ab mode: checkpointed warm-start vs cold restart at the
    headline node tier (BENCH_WARM_NODES, default 100k nodes with one
    bound pod each), over the real wire and across real process
    boundaries.  The parent seeds an in-process apiserver (HTTP front
    door, same shape as the procrun children's), builds the pre-drain
    mirror and cuts its checkpoint; then each restart path runs in its
    own FRESH interpreter (warm_ab_child_main) — the shape of an actual
    scheduler restart, and the only heap state that measures restart
    allocation costs honestly.

      cold   wire LIST of nodes+pods (HTTP + JSON decode — what a
             restarted child without --warm-dir pays to re-seed its
             informers) + fresh Cache prime + full flatten encode of
             every row
      warm   checkpoint read (magic/version/crc gates + unpickle) +
             fresh Cache primed from the checkpoint's objects +
             warm_align digest sweep — no LIST, no re-encode; the
             informer delta since the checkpoint resourceVersion is
             empty here and costs the same on both sides

    Both sides end with the resident mirror current and the first full
    device upload still pending (identical either way, so excluded).
    The device-mirror-rebuild ratio (full encode vs checkpoint load +
    adopt sweep, no object acquisition on either side) is reported
    separately."""
    import subprocess
    import tempfile

    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import LocalClient
    from kubernetes_tpu.client.clientset import NODES, PODS
    from kubernetes_tpu.client.http_client import HTTPClient
    from kubernetes_tpu.ops.backend import TPUBatchBackend
    from kubernetes_tpu.perf import caps_for_nodes
    from kubernetes_tpu.scheduler.cache import Cache
    from kubernetes_tpu.store import kv
    from kubernetes_tpu.testing import make_node, make_pod

    n_nodes = int(os.environ.get("BENCH_WARM_NODES", "100000"))
    n_pods = int(os.environ.get("BENCH_WARM_PODS", str(n_nodes)))
    batch = int(os.environ.get("BENCH_WARM_BATCH", "16384"))
    caps = caps_for_nodes(n_nodes)

    store = kv.MemoryStore(history=1_000_000)
    seed = LocalClient(store)  # population only; the A/B lists over HTTP
    for i in range(n_nodes):
        seed.create(NODES, make_node(f"n{i}")
                    .capacity(cpu="16", mem="64Gi", pods=110)
                    .labels(**{"topology.kubernetes.io/zone": f"z{i % 16}"})
                    .build())
    for i in range(n_pods):
        p = make_pod(f"p{i}").req(cpu="100m", mem="128Mi").build()
        p["spec"]["nodeName"] = f"n{i % n_nodes}"
        seed.create(PODS, p)
    server = APIServer(store).start()
    http = HTTPClient.from_url(server.url)
    try:
        # the pre-drain process: mirror current, then the drain checkpoint
        nodes_a, _ = http.list(NODES)
        pods_a, _ = http.list(PODS)
        cache_a = Cache()
        for o in nodes_a:
            cache_a.add_node(o)
        for p in pods_a:
            cache_a.add_pod(p)
        backend_a = TPUBatchBackend(caps, batch_size=batch)
        backend_a.tensors.update_from_snapshot_tracked(
            cache_a.flatten_view())
        path = os.path.join(tempfile.mkdtemp(prefix="ktpu-warm-ab-"),
                            "sched-0.ckpt")
        t0 = time.monotonic()
        backend_a.checkpoint_mirror(
            path, snapshot=cache_a.flatten_view(),
            resource_versions={}, objects={NODES: nodes_a, PODS: pods_a})
        t_checkpoint = time.monotonic() - t0

        env = dict(os.environ,
                   BENCH_AB_URL=server.url, BENCH_AB_CKPT=path,
                   BENCH_AB_NODES=str(n_nodes), BENCH_AB_BATCH=str(batch))
        sides = {}
        for side in ("cold", "warm"):
            env["_BENCH_WARM_AB_CHILD"] = side
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               capture_output=True, text=True, env=env)
            if r.returncode != 0:
                raise RuntimeError(
                    f"warm-ab {side} child failed:\n{r.stderr[-2000:]}")
            sides[side] = json.loads(r.stdout.strip().splitlines()[-1])
    finally:
        server.stop()

    cold, warm = sides["cold"], sides["warm"]
    t_cold, t_warm = cold["total_s"], warm["total_s"]
    return {
        "nodes": n_nodes, "pods": n_pods, "batch": batch,
        "checkpoint_bytes": os.path.getsize(path),
        "checkpoint_write_s": round(t_checkpoint, 3),
        "cold": cold,
        "warm": warm,
        "speedup_end_to_end": round(t_cold / max(t_warm, 1e-9), 2),
        "speedup_mirror_rebuild": round(
            cold["full_encode_s"]
            / max(warm["load_s"] + warm["adopt_sweep_s"], 1e-9), 2),
    }


def run_scaleout(max_instances: int) -> dict:
    """--instances N: horizontal scale-out A/B.  1, 2, ... N cooperating
    scheduler instances (each with its own informers, cache, queue and
    device backend) share ONE MemoryStore — the Omega shared-state shape
    — and drain the Scheduling100k-scale flood together.  Instances >1
    partition nodes AND pods over the scaleOut node-pool ring, so the
    steady-state conflict rate should be ~0; every optimistic-bind loss
    that does happen is counted via scheduler_bind_conflict_total and
    reported as conflict_rate (conflicted pod-events / pods).

    In-process by design (same trade as --trace/--overload): N
    interpreters would each pay the device warmup, and the instances
    must share a store object.  Shrink with BENCH_SCALEOUT_NODES/PODS
    for smoke runs."""
    from kubernetes_tpu.client.clientset import NODES, PODS, LocalClient
    from kubernetes_tpu.perf import caps_for_nodes
    from kubernetes_tpu.perf.scheduler_perf import (
        ThroughputCollector, setup_cluster,
    )
    from kubernetes_tpu.scheduler.config import ScaleOutPolicy
    from kubernetes_tpu.store import kv
    from kubernetes_tpu.testing import make_node, make_pod

    nodes = int(os.environ.get("BENCH_SCALEOUT_NODES", "100000"))
    pods = int(os.environ.get("BENCH_SCALEOUT_PODS", "200000"))
    batch = int(os.environ.get("BENCH_SCALEOUT_BATCH", "16384"))
    timeout = float(os.environ.get("BENCH_SCALEOUT_TIMEOUT", "1200"))

    def one_pass(n: int) -> dict:
        store = kv.MemoryStore(history=2_000_000)
        admin = LocalClient(store)
        # each instance tracks only ~1/n of the ring, so its backend's
        # node capacity shrinks with n (1.6/n covers crc32 slice skew)
        caps = caps_for_nodes(
            nodes if n == 1 else min(nodes, int(nodes * 1.6 / n) + 256))
        clusters = []
        for i in range(n):
            cl = setup_cluster(tpu=True, caps=caps, batch_size=batch,
                               store=store, pipeline_depth=2)
            if n > 1:
                cl.scheduler.configure_scaleout(ScaleOutPolicy(
                    instance_count=n, instance_index=i,
                    ring_slices=max(64, 16 * n)))
            clusters.append(cl)
        try:
            CHUNK = 10_000
            for lo in range(0, nodes, CHUNK):
                admin.create_bulk(NODES, [
                    make_node(f"sn-{i}")
                    .capacity(cpu="64", mem="256Gi", pods=1000).build()
                    for i in range(lo, min(lo + CHUNK, nodes))])
            # let every instance fold its node partition into its host
            # tensors before the flood (same reason as the idle prefetch)
            time.sleep(1.0 + nodes / 50_000)
            collector = ThroughputCollector(store)
            collector.start()
            t0 = time.monotonic()
            for lo in range(0, pods, CHUNK):
                admin.create_bulk(PODS, [
                    make_pod(f"sp-{i}").req(cpu="10m", mem="16Mi").build()
                    for i in range(lo, min(lo + CHUNK, pods))])
            ok = False
            while time.monotonic() - t0 < timeout:
                if collector.bound_total() >= pods:
                    ok = True
                    break
                time.sleep(0.25)
            elapsed = time.monotonic() - t0
            collector.stop()
            # cross-process metrics federation: one merged view over every
            # instance's /metrics exposition text (the scale-out phase-2
            # aggregation path; in-process here, but through the same
            # parse-and-sum code an HTTP-pull federator would run)
            from kubernetes_tpu.component_base.profiling import federate_texts
            fleet = federate_texts(
                cl.scheduler.expose_metrics() for cl in clusters)
            conflicts = {
                labels[0]: v for labels, v in
                fleet.get("scheduler_bind_conflict_total", {}).items()}
            row = {"pods_per_s": round(pods / elapsed, 1) if ok else 0.0,
                   "wall_s": round(elapsed, 1),
                   "bound": collector.bound_total(),
                   "conflicts": {k: int(v) for k, v in
                                 sorted(conflicts.items())},
                   "conflict_rate": round(
                       sum(conflicts.values()) / max(pods, 1), 6)}
            if not ok:
                row["error"] = "pods left unscheduled"
            return row
        finally:
            for cl in clusters:
                cl.shutdown()

    counts = [c for c in (1, 2, 4) if c <= max_instances]
    if max_instances not in counts:
        counts.append(max_instances)
    instances: dict[str, dict] = {}
    for n in counts:
        instances[str(n)] = one_pass(n)
    base = instances.get("1", {}).get("pods_per_s") or 0.0
    for row in instances.values():
        if base and row.get("pods_per_s"):
            row["speedup_vs_1"] = round(row["pods_per_s"] / base, 2)
    return {"nodes": nodes, "pods": pods, "batch": batch,
            "BENCH_SCALEOUT": instances}


def run_scaleout_proc(max_procs: int) -> dict:
    """--processes N: PROCESS-TRUE scale-out A/B.  Unlike --instances
    (N schedulers sharing one interpreter and one MemoryStore object),
    this spawns one real apiserver process plus 1, 2, ... N scheduler
    OS processes via the procrun supervisor — every list/watch/bind
    crosses an actual process boundary over HTTP, so the numbers carry
    serialization, socket and GIL-free costs the in-process row hides.

    Null-device on purpose: N child interpreters would each pay the
    device warmup, and the question this row answers is whether the
    CONTROL PLANE scales across processes, not whether N chips do.
    Exactly-once is proved per pass by a store-watch WireBindLedger
    (zero double-binds, zero lost pods), and every multi-process count
    re-validates it under a seeded crash->failover churn sub-pass.
    Shrink with BENCH_SCALEOUT_NODES/PODS for smoke runs."""
    from kubernetes_tpu.client.clientset import NODES, PODS
    from kubernetes_tpu.component_base.profiling import federate_texts
    from kubernetes_tpu.ops.faults import (
        KILL_INSTANCE, ProcessChurner, ScaleOutSchedule,
    )
    from kubernetes_tpu.scheduler.procrun import ProcCluster, WireBindLedger
    from kubernetes_tpu.testing import make_node, make_pod

    nodes = int(os.environ.get("BENCH_SCALEOUT_NODES", "20000"))
    pods = int(os.environ.get("BENCH_SCALEOUT_PODS", "60000"))
    batch = int(os.environ.get("BENCH_SCALEOUT_BATCH", "4096"))
    timeout = float(os.environ.get("BENCH_SCALEOUT_TIMEOUT", "1200"))
    CHUNK = 10_000

    def one_pass(n: int) -> dict:
        cluster = ProcCluster(n, backend="null", batch_size=batch,
                              nodes=nodes)
        try:
            cluster.start()
            admin = cluster.admin_client()
            for lo in range(0, nodes, CHUNK):
                admin.create_bulk(NODES, [
                    make_node(f"sn-{i}")
                    .capacity(cpu="64", mem="256Gi", pods=1000).build()
                    for i in range(lo, min(lo + CHUNK, nodes))])
            # let every child replicate its node partition over the wire
            # before the flood (the in-process pass sleeps for the same
            # reason; here the watch stream adds HTTP latency on top)
            time.sleep(2.0 + nodes / 20_000)
            ledger = WireBindLedger(admin)
            t0 = time.monotonic()
            for lo in range(0, pods, CHUNK):
                admin.create_bulk(PODS, [
                    make_pod(f"sp-{i}").req(cpu="10m", mem="16Mi").build()
                    for i in range(lo, min(lo + CHUNK, pods))])
            ok = False
            while time.monotonic() - t0 < timeout:
                if ledger.bound_total() >= pods:
                    ok = True
                    break
                time.sleep(0.25)
            elapsed = time.monotonic() - t0
            try:
                ledger.assert_no_double_binds()
                double_binds: int | str = 0
            except AssertionError as e:  # record, don't abort the sweep
                double_binds = str(e)[:500]
            fleet = federate_texts(cluster.metrics_texts())
            conflicts = {
                labels[0]: v for labels, v in
                fleet.get("scheduler_bind_conflict_total", {}).items()}
            row = {"pods_per_s": round(pods / elapsed, 1) if ok else 0.0,
                   "wall_s": round(elapsed, 1),
                   "bound": ledger.bound_total(),
                   "double_binds": double_binds,
                   "conflicts": {k: int(v) for k, v in
                                 sorted(conflicts.items())},
                   "conflict_rate": round(
                       sum(conflicts.values()) / max(pods, 1), 6)}
            if not ok:
                row["error"] = "pods left unscheduled"
            # seeded crash->failover validation: SIGKILL one child, then
            # prove the survivors drain a fresh flood with the same
            # exactly-once guarantees (ledger stays live: it has seen
            # every bind since rv=0, so double-binds across the crash
            # boundary are visible too)
            if n > 1 and ok:
                churner = ProcessChurner(
                    cluster,
                    ScaleOutSchedule(seed=13, instance_count=n,
                                     script={0: (KILL_INSTANCE, 0)}),
                    min_live=1)
                applied = churner.step()
                extra = max(1000, pods // 20)
                for lo in range(pods, pods + extra, CHUNK):
                    admin.create_bulk(PODS, [
                        make_pod(f"sp-{i}")
                        .req(cpu="10m", mem="16Mi").build()
                        for i in range(lo, min(lo + CHUNK, pods + extra))])
                c0 = time.monotonic()
                c_ok = False
                while time.monotonic() - c0 < timeout:
                    if ledger.bound_total() >= pods + extra:
                        c_ok = True
                        break
                    time.sleep(0.25)
                try:
                    ledger.assert_no_double_binds()
                    c_doubles: int | str = 0
                except AssertionError as e:
                    c_doubles = str(e)[:500]
                row["churn"] = {
                    "applied": list(applied) if applied else None,
                    "extra_pods": extra,
                    "bound_after": ledger.bound_total(),
                    "zero_lost": c_ok,
                    "double_binds": c_doubles,
                    "wall_s": round(time.monotonic() - c0, 1)}
            ledger.stop()
            return row
        finally:
            cluster.shutdown()

    counts = [c for c in (1, 2, 4) if c <= max_procs]
    if max_procs not in counts:
        counts.append(max_procs)
    procs: dict[str, dict] = {}
    for n in counts:
        procs[str(n)] = one_pass(n)
    base = procs.get("1", {}).get("pods_per_s") or 0.0
    for row in procs.values():
        if base and row.get("pods_per_s"):
            row["speedup_vs_1"] = round(row["pods_per_s"] / base, 2)
    return {"nodes": nodes, "pods": pods, "batch": batch,
            "host_cores": os.cpu_count(),
            "BENCH_SCALEOUT_PROC": procs}


def run_once(workload: str, nodes: int | None, pods: int | None,
             batch: int, barrier_timeout: float = 900.0,
             rate: float | None = None, depth: int = 1,
             admission_ms: float = 0.0, via_http: bool = False,
             null_device: bool = False, pct_nodes: int = 0,
             overload: bool = False, backend_kind: str = "tpu",
             census: bool = False, timeline: bool = False) -> dict:
    """One full workload pass in this process; returns the result dict."""
    import copy

    from kubernetes_tpu.perf import (
        caps_for_nodes, load_workloads, run_named_workload,
    )
    from kubernetes_tpu.perf.scheduler_perf import is_measured

    cfg = copy.deepcopy(load_workloads()[workload])
    tpl = cfg["workloadTemplate"]
    # count/rate overrides target the MEASURED createPods only: warm-up
    # ops (no collectMetrics; see performance-config.yaml) keep their
    # small configured size
    for op in tpl:
        measured = is_measured(op, tpl)
        if op["opcode"] == "createNodes" and nodes is not None:
            op["count"] = nodes
        elif op["opcode"] == "createPods" and measured and pods is not None:
            op["count"] = pods
        if op["opcode"] == "createPods" and measured and rate:
            op["ratePerSecond"] = rate
    # barrier/freeze budget scales with the measured pod count (the
    # config's timeout stays the floor): set AFTER the count overrides
    # so the scale sees the pods that will actually be created
    n_measured = sum(op["count"] for op in tpl
                     if op["opcode"] == "createPods"
                     and is_measured(op, tpl))
    for op in tpl:
        if op["opcode"] == "barrier":
            op["timeout"] = scaled_timeout(n_measured, barrier_timeout)
    n_nodes = next(op["count"] for op in cfg["workloadTemplate"]
                   if op["opcode"] == "createNodes")

    caps = caps_for_nodes(n_nodes)  # THE shared cap policy (perf/__init__)
    policy = chaos = None
    if overload:
        policy, chaos = _overload_shape(batch)
    profiling_policy = None
    if census or timeline:
        # census=True arms run_device_census() after warmup so the row
        # carries tpu_wave_collective_bytes — the in-band pin of the
        # collective-byte budget (bit-for-bit vs tools/collective_census.py).
        # timeline=True arms the wave-timeline interval ring so the row
        # carries device_idle_share + the per-pod latency decomposition.
        from kubernetes_tpu.scheduler.config import ProfilingPolicy
        profiling_policy = ProfilingPolicy(census=census, timeline=timeline)
    t0 = time.monotonic()
    summary, stats = run_named_workload(cfg, tpu=True, caps=caps,
                                        batch_size=batch,
                                        pipeline_depth=depth,
                                        admission_interval=admission_ms / 1e3,
                                        via_http=via_http,
                                        null_device=null_device,
                                        percentage_of_nodes_to_score=pct_nodes,
                                        backend_kind=backend_kind,
                                        overload=policy,
                                        chaos_schedule=chaos,
                                        profiling_policy=profiling_policy)
    wall = time.monotonic() - t0
    if not stats.get("barrier_ok", False):
        return {"error": "pods left unscheduled", "value": 0.0,
                "detail": summary.to_dict()}
    detail = summary.to_dict()
    e2e = stats.get("e2e") or {}
    if e2e:
        # every BENCH row carries the full quantile triple, not just
        # --profile runs: p95 is the knee the latency plots track
        detail["pod_e2e_p50_ms"] = e2e.get("p50_ms")
        detail["pod_e2e_p95_ms"] = e2e.get("p95_ms")
        detail["pod_e2e_p99_ms"] = e2e.get("p99_ms")
    if "escape_rate" in stats:
        # escaped-to-oracle fraction (tensor-path coverage; target <5%)
        detail["escape_rate"] = stats["escape_rate"]
    if "preemption_attempts" in stats:
        detail["preemption_attempts"] = stats["preemption_attempts"]
    maint = stats.get("tensor_maintenance")
    if maint:
        # incremental flatten: how the resident device tensors were kept
        # current — patched-in-place vs full re-flatten wave counts, and
        # the two maintenance stages' share of the run's wall time
        patch_s = float(maint.get("patch_seconds", 0.0))
        flat_s = float(maint.get("flatten_seconds", 0.0))
        detail["tensor_maintenance"] = {
            "waves_patched": maint.get("waves_patched", 0),
            "waves_reflattened": maint.get("waves_reflattened", 0),
            "event_patches": maint.get("event_patches", 0),
            "compactions": maint.get("compactions", 0),
            "gen_stale_waves": maint.get("gen_stale_waves", 0),
            "snapshot_patch_s": round(patch_s, 3),
            "snapshot_flatten_s": round(flat_s, 3),
            "host_share": round((patch_s + flat_s) / wall, 4) if wall else 0.0,
        }
    if "overload" in stats:
        detail["overload"] = stats["overload"]
    if "chaos_injected" in stats:
        detail["chaos_injected"] = stats["chaos_injected"]
    if backend_kind != "tpu":
        detail["backend"] = backend_kind
    if census and stats.get("device_census"):
        from kubernetes_tpu.component_base.profiling import (
            collective_bytes_by_op,
        )
        gauges: dict[str, dict] = {}
        for kind, recs in stats["device_census"].items():
            for variant, rec in recs.items():
                per_wave, per_call = collective_bytes_by_op(rec)
                gauges[f"{kind}-{variant}"] = {
                    "per_wave_bytes": rec.get("per_wave_bytes", 0),
                    "tpu_wave_collective_bytes": per_wave,
                    "tpu_step_collective_bytes": per_call,
                }
        detail["tpu_wave_collective_bytes"] = gauges
    tl_stats = stats.get("timeline")
    if tl_stats:
        # wave-timeline read-out: the union-derived idle share (correct
        # under pipelining, unlike 1 - Σ stage_seconds / wall), per-stage
        # overlap ratios and the telescoped per-pod segment quantiles
        detail["device_idle_share"] = tl_stats.get("device_idle_share")
        detail["stage_overlap"] = tl_stats.get("overlap")
        detail["latency_decomposition"] = tl_stats.get("segments")
        detail["timeline_intervals"] = tl_stats.get("intervals")
    return {"value": summary.average, "wall_s": round(wall, 1),
            "detail": detail}


def emit(value: float, extra: dict) -> None:
    print(json.dumps({
        "metric": "scheduler_perf_throughput",
        "value": round(value, 1),
        "unit": "pods/s",
        "vs_baseline": round(value / BASELINE_PODS_PER_SEC, 2),
        "detail": {"nodes": N_NODES, "pods": N_PODS, "batch": BATCH,
                   **extra},
    }))


def _spawn_child(env_extra: dict, timeout: float) -> dict | None:
    env = dict(os.environ, _BENCH_CHILD="1", **env_extra)
    for attempt in (1, 2):  # one retry: tunnel hiccups are transient
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, env=env, timeout=timeout,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            continue
        if proc.returncode == 0:
            try:
                return json.loads(proc.stdout.strip().splitlines()[-1])
            except (json.JSONDecodeError, IndexError):
                continue
        sys.stderr.write(proc.stderr[-2000:])
        if attempt == 2 and proc.stdout.strip():
            try:  # relay the child's own error JSON
                return json.loads(proc.stdout.strip().splitlines()[-1])
            except json.JSONDecodeError:
                pass
    return None


def child_main() -> None:
    seam = os.environ.get("_BENCH_W_SEAM")
    if seam:
        res = run_seam_micro(seam,
                             faulty=bool(os.environ.get("_BENCH_W_FAULTY")))
        emit(res["remote_pods_per_s"], {"seam": seam, **res})
        return
    name = os.environ.get("_BENCH_WORKLOAD", "SchedulingBasicLarge")
    nodes = os.environ.get("_BENCH_W_NODES")
    pods = os.environ.get("_BENCH_W_PODS")
    batch = int(os.environ.get("_BENCH_W_BATCH", str(BATCH)))
    rate = os.environ.get("_BENCH_W_RATE")
    res = run_once(name, int(nodes) if nodes else None,
                   int(pods) if pods else None, batch,
                   float(os.environ.get("_BENCH_W_TIMEOUT", "900")),
                   rate=float(rate) if rate else None,
                   depth=int(os.environ.get("_BENCH_W_DEPTH", "1")),
                   admission_ms=float(os.environ.get("_BENCH_W_ADMISSION_MS",
                                                     "0")),
                   via_http=("process"
                             if os.environ.get("_BENCH_W_HTTP") == "proc"
                             else os.environ.get("_BENCH_W_HTTP") == "1"),
                   null_device=os.environ.get("_BENCH_W_NULL") == "1",
                   pct_nodes=int(os.environ.get("_BENCH_W_PCT", "0")),
                   overload=os.environ.get("_BENCH_W_OVERLOAD") == "1",
                   backend_kind=os.environ.get("_BENCH_W_BACKEND", "tpu"),
                   census=os.environ.get("_BENCH_W_CENSUS") == "1",
                   timeline=os.environ.get("_BENCH_W_TIMELINE") == "1")
    if "error" in res:
        emit(0.0, {"error": res["error"], **res["detail"]})
        sys.exit(1)
    emit(res["value"], {"wall_s": res["wall_s"], **res["detail"]})


def _device_reachable(timeout: float = 180.0) -> bool:
    """Probe the device in a subprocess BEFORE spending child timeouts.

    A dead chip tunnel blocks jax.devices() forever (observed: a full
    day of make_c_api_client hangs); without this probe every bench
    child would burn its entire barrier timeout twice before failing."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices()"],
            capture_output=True, timeout=timeout)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _config_env(c: dict) -> dict:
    env = {"_BENCH_WORKLOAD": c["workload"],
           "_BENCH_W_BATCH": str(c["batch"]),
           "_BENCH_W_TIMEOUT": str(c.get("timeout", 900.0))}
    if "nodes" in c:
        env["_BENCH_W_NODES"] = str(c["nodes"])
    if "pods" in c:
        env["_BENCH_W_PODS"] = str(c["pods"])
    if "rate" in c:
        env["_BENCH_W_RATE"] = str(c["rate"])
    if "depth" in c:
        env["_BENCH_W_DEPTH"] = str(c["depth"])
    if "admission_ms" in c:
        env["_BENCH_W_ADMISSION_MS"] = str(c["admission_ms"])
    if c.get("http"):
        env["_BENCH_W_HTTP"] = "proc" if c["http"] == "proc" else "1"
    if c.get("null"):
        env["_BENCH_W_NULL"] = "1"
    if c.get("pct_nodes"):
        env["_BENCH_W_PCT"] = str(c["pct_nodes"])
    if c.get("overload"):
        env["_BENCH_W_OVERLOAD"] = "1"
    if c.get("backend"):
        env["_BENCH_W_BACKEND"] = c["backend"]
    if c.get("census"):
        env["_BENCH_W_CENSUS"] = "1"
    if c.get("timeline"):
        env["_BENCH_W_TIMELINE"] = "1"
    return env


def main() -> None:
    if os.environ.get("_BENCH_CHILD") == "1":
        child_main()
        return
    if os.environ.get("_BENCH_WARM_AB_CHILD") in ("cold", "warm"):
        warm_ab_child_main(os.environ["_BENCH_WARM_AB_CHILD"])
        return
    if "--trace" in sys.argv:
        # in-process by design: the Chrome export needs the scheduler's
        # and the in-process worker's span rings in one interpreter
        idx = sys.argv.index("--trace")
        out = (sys.argv[idx + 1] if len(sys.argv) > idx + 1
               and not sys.argv[idx + 1].startswith("-") else None)
        res = run_trace(out)
        emit(res["traced_pods_per_s"], {"mode": "trace", **res})
        return
    if "--profile" in sys.argv:
        # in-process by design (same trade as --trace): the profiled and
        # unprofiled sides share one warmed interpreter + device so the
        # sampler-overhead ratio isn't polluted by a second cold start
        idx = sys.argv.index("--profile")
        out = (sys.argv[idx + 1] if len(sys.argv) > idx + 1
               and not sys.argv[idx + 1].startswith("-") else None)
        res = run_profile(out)
        emit(res["profiled_pods_per_s"], {"mode": "profile", **res})
        return
    if "--timeline" in sys.argv:
        # in-process A/B by design (same trade as --profile): the armed
        # and disarmed sides share one warmed interpreter + device so
        # the ring-overhead ratio isn't polluted by a second cold start
        idx = sys.argv.index("--timeline")
        out = (sys.argv[idx + 1] if len(sys.argv) > idx + 1
               and not sys.argv[idx + 1].startswith("-") else None)
        res = run_timeline(out)
        emit(res["timed_pods_per_s"], {"mode": "timeline", **res})
        return
    if "--pipeline-ab" in sys.argv:
        # in-process A/B by design (same trade as --timeline): both
        # depths share one warmed interpreter + device so the pipeline
        # gap isn't polluted by a second cold start
        res = run_pipeline_ab()
        emit(res["depth2"]["pods_per_s"], {"mode": "pipeline_ab", **res})
        return
    if "--overload" in sys.argv:
        # in-process A/B by design (same trade as --trace): both sides
        # share one warmed interpreter + device so the policy gap isn't
        # polluted by a second cold start
        res = run_overload()
        emit(res["with_policy"]["pods_per_s"], {"mode": "overload", **res})
        return
    if "--warm-ab" in sys.argv:
        # process-true A/B: each restart path runs in a fresh
        # interpreter (warm_ab_child_main) — a restart IS a fresh
        # process, and the warmed parent's fragmented heap would
        # overstate warm load ~10x
        res = run_warm_ab()
        emit(res["speedup_end_to_end"], {"mode": "warm_ab", **res})
        return
    if "--instances" in sys.argv:
        idx = sys.argv.index("--instances")
        n = (int(sys.argv[idx + 1]) if len(sys.argv) > idx + 1
             and sys.argv[idx + 1].isdigit() else 2)
        res = run_scaleout(n)
        best = max((row.get("pods_per_s") or 0.0)
                   for row in res["BENCH_SCALEOUT"].values())
        emit(best, {"mode": "scaleout", **res})
        return
    if "--processes" in sys.argv:
        # before the device check on purpose: the process-true row is
        # null-device (control-plane scaling, not chip scaling) and must
        # keep reporting when the chip tunnel is down
        idx = sys.argv.index("--processes")
        n = (int(sys.argv[idx + 1]) if len(sys.argv) > idx + 1
             and sys.argv[idx + 1].isdigit() else 2)
        res = run_scaleout_proc(n)
        best = max((row.get("pods_per_s") or 0.0)
                   for row in res["BENCH_SCALEOUT_PROC"].values())
        emit(best, {"mode": "scaleout-proc", **res})
        return
    if not _device_reachable():
        # The chip tunnel is down — but null-device configs measure the
        # HOST ceiling and never touch jax: they must not go dark with
        # the tunnel (they are the row that keeps tracking the
        # single-interpreter wall through bad weather).
        configs: dict[str, dict] = {}
        for cname, c in EXTRA_CONFIGS.items():
            if not c.get("null"):
                continue
            got = _spawn_child(_config_env(c),
                               timeout=c.get("timeout", 900.0) + 300)
            d = (got or {}).get("detail", {})
            configs[cname] = ({"pods_per_s": got.get("value", 0.0),
                               "total_pods": d.get("TotalPods")}
                              if got else {"error": "failed"})
        emit(0.0, {"error": "device unreachable: jax.devices() did not "
                            "return within 180s (chip tunnel down?)",
                   "configs": configs})
        sys.exit(1)
    n_runs = max(1, int(os.environ.get("BENCH_RUNS", "3")))
    backend_kind = os.environ.get("BENCH_BACKEND", "sharded")
    if "--backend" in sys.argv:
        idx = sys.argv.index("--backend")
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            backend_kind = sys.argv[idx + 1]
    head_nodes = int(os.environ.get("BENCH_HEAD_NODES", "100000"))
    head_pods = int(os.environ.get("BENCH_HEAD_PODS", "200000"))
    if n_runs == 1:
        res = run_once("SchedulingBasicLarge", head_nodes, head_pods, BATCH,
                       barrier_timeout=1800.0, depth=DEPTH,
                       backend_kind=backend_kind, census=True,
                       timeline=True)
        if "error" in res:
            emit(0.0, {"error": res["error"], "nodes": head_nodes,
                       "pods": head_pods, **res["detail"]})
            sys.exit(1)
        emit(res["value"], {"wall_s": res["wall_s"], "nodes": head_nodes,
                            "pods": head_pods, **res["detail"]})
        return

    t0 = time.monotonic()
    # HEADLINE: Scheduling100k through the sharded backend (node tensors
    # partitioned per NODE_PARTITION_RULES, conflict matrices resolved by
    # reduce-scatter) with the census gauges carried in-row.  ONE pass —
    # the 100k tier's budget note on EXTRA_CONFIGS applies doubly here.
    head_cfg = {"workload": "SchedulingBasicLarge", "nodes": head_nodes,
                "pods": head_pods, "batch": BATCH, "depth": DEPTH,
                "timeout": 1800.0, "backend": backend_kind, "census": True,
                "timeline": True}
    head = _spawn_child(_config_env(head_cfg),
                        timeout=scaled_timeout(head_pods, 1800.0) + 300)
    if head is None:
        emit(0.0, {"error": "bench headline child failed twice"})
        sys.exit(1)
    if head.get("value", 0.0) == 0.0:
        emit(0.0, head.get("detail", {"error": "headline child failed"}))
        sys.exit(1)

    # trajectory row: the BENCH_r01-r05 headline shape (5k-node
    # SchedulingBasic, single-chip, median of n_runs) so the series
    # stays comparable across the backend switch
    results: list[dict] = []
    basic_env = {"_BENCH_WORKLOAD": "SchedulingBasicLarge",
                 "_BENCH_W_NODES": str(N_NODES),
                 "_BENCH_W_PODS": str(N_PODS),
                 "_BENCH_W_BATCH": str(BATCH),
                 "_BENCH_W_DEPTH": str(DEPTH)}
    for _ in range(n_runs):
        # margin over the child's (pod-scaled) barrier so a stuck child
        # still gets to emit its own error JSON before the parent gives up
        got = _spawn_child(basic_env,
                           timeout=scaled_timeout(N_PODS, 900.0) + 300)
        if got is None:
            emit(0.0, {"error": "bench child failed twice"})
            sys.exit(1)
        if got.get("value", 0.0) == 0.0:
            emit(0.0, got.get("detail", {"error": "child failed"}))
            sys.exit(1)
        results.append(got)

    configs: dict[str, dict] = {}
    if os.environ.get("BENCH_SUITE", "full") != "basic":
        for cname, c in EXTRA_CONFIGS.items():
            if "seam" in c:
                env = {"_BENCH_W_SEAM": c["seam"]}
                if c.get("faulty"):
                    env["_BENCH_W_FAULTY"] = "1"
                got = _spawn_child(env,
                                   timeout=c.get("timeout", 600.0) + 300)
                configs[cname] = (got.get("detail", {"error": "failed"})
                                  if got else {"error": "failed"})
                continue
            env = _config_env(c)
            got = _spawn_child(
                env, timeout=scaled_timeout(
                    c.get("pods"), c.get("timeout", 900.0)) + 300)
            # best-of-2 for the quick configs that opt in ("two_pass"):
            # the tunnel's round-trip latency drifts 2-3x over minutes,
            # and one pass landing in a bad-weather window misreports
            # the config by the same factor (observed: TopologySpreading
            # 1.1k mid-suite vs 8-9k solo minutes later).  Rate-paced
            # configs hold throughput at the pacing rate by design, so
            # for them "better" means lower p99 latency, not higher
            # pods/s.  Both passes are recorded.
            if got is None and c.get("two_pass"):
                # a first pass lost entirely to a transient failure is
                # the same weather the two-pass feature targets: give
                # the config its second attempt instead of reporting
                # {"error": "failed"} without one
                got = _spawn_child(env, timeout=c.get("timeout", 900.0)
                                   + 300)
            elif got is not None and c.get("two_pass"):
                got2 = _spawn_child(env, timeout=c.get("timeout", 900.0)
                                    + 300)
                if got2 is not None:
                    if "rate" in c:
                        k = lambda g: (g.get("detail", {})
                                       .get("pod_e2e_p99_ms") or 1e12)
                        better = k(got2) < k(got)
                    else:
                        better = (got2.get("value", 0.0)
                                  > got.get("value", 0.0))
                    if better:
                        got, got2 = got2, got
                    d2 = got2.get("detail", {})
                    got.setdefault("detail", {})["second_pass"] = {
                        "pods_per_s": round(got2.get("value", 0.0), 1),
                        "p99_ms": d2.get("pod_e2e_p99_ms")}
            if got is None:
                configs[cname] = {"error": "failed"}
                continue
            d = got.get("detail", {})
            configs[cname] = {
                "pods_per_s": got.get("value", 0.0),
                "p50_ms": d.get("pod_e2e_p50_ms"),
                "p99_ms": d.get("pod_e2e_p99_ms"),
                "total_pods": d.get("TotalPods"),
            }
            if "escape_rate" in d:
                configs[cname]["escape_rate"] = d["escape_rate"]
            if "preemption_attempts" in d:
                configs[cname]["preemption_attempts"] = d["preemption_attempts"]
            if "second_pass" in d:
                configs[cname]["second_pass"] = d["second_pass"]

    wall = time.monotonic() - t0
    results.sort(key=lambda r: r["value"])
    med = results[len(results) // 2]
    configs["SchedulingBasicSingleChip"] = {
        "pods_per_s": med["value"], "runs": n_runs,
        "averages": [r["value"] for r in results],
        "p50_ms": med["detail"].get("pod_e2e_p50_ms"),
        "p99_ms": med["detail"].get("pod_e2e_p99_ms"),
        "total_pods": med["detail"].get("TotalPods")}
    emit(head["value"], {"wall_s": round(wall, 1),
                         "nodes": head_nodes, "pods": head_pods,
                         "configs": configs,
                         **{k: v for k, v in head["detail"].items()
                            if k not in ("nodes", "pods", "wall_s")}})


if __name__ == "__main__":
    main()
