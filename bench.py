#!/usr/bin/env python
"""Benchmark entry point (driver contract: ONE JSON line on stdout).

Runs the scheduler_perf SchedulingBasic workload (reference:
test/integration/scheduler_perf, 5000 nodes scale from
config/performance-config.yaml, pod count raised to 20k for stable
sampling) through the FULL pipeline — store -> watch -> informers ->
queue -> TPU batch Filter/Score/Assign -> assume -> bind — and reports
end-to-end scheduling throughput.

Methodology: BENCH_RUNS (default 3) independent passes, each in a FRESH
subprocess (its own interpreter, jax client, and device state — runs in
one process interfere through allocator/device-buffer state), reporting
the median.  BENCH_RUNS=1 or _BENCH_CHILD=1 runs a single in-process
pass.

Baseline: the reference tree publishes no absolute numbers (BASELINE.md);
upstream Kubernetes scheduler_perf results for the 5k-node SchedulingBasic
tier sit around ~300 pods/s steady-state on a large single box (public
perf-dash data; the in-tree comment scheduler_perf_test.go:956 notes a
~10 pods/s worst case).  vs_baseline uses 300 pods/s as the reference
point.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_PODS_PER_SEC = 300.0

N_NODES = int(os.environ.get("BENCH_NODES", "5000"))
# 50k pods: at ~10k+ pods/s a 20k-pod run is half pipeline ramp; 50k gives
# ~5s of steady state under the 1s sampling window (same tracked config,
# same stable-sampling rationale as the r01 10k->20k bump)
N_PODS = int(os.environ.get("BENCH_PODS", "50000"))
# 16384 is the largest batch whose [P,N] working set fits v5e HBM at 5k
# nodes (24576 exceeds 15.75G); with the GC fix the bigger batch wins on
# both throughput AND backlog-drain latency
BATCH = int(os.environ.get("BENCH_BATCH", "16384"))


def run_once() -> dict:
    """One full workload pass in this process; returns the result dict."""
    import copy

    from kubernetes_tpu.ops.flatten import Caps
    from kubernetes_tpu.perf import load_workloads, run_named_workload

    cfg = copy.deepcopy(load_workloads()["SchedulingBasicLarge"])
    for op in cfg["workloadTemplate"]:
        if op["opcode"] == "createNodes":
            op["count"] = N_NODES
        elif op["opcode"] == "createPods":
            op["count"] = N_PODS
        elif op["opcode"] == "barrier":
            op["timeout"] = 900.0

    n_cap = max(1024, -(-int(N_NODES * 1.1) // 256) * 256)  # ~10% headroom
    caps = Caps(n_cap=n_cap,
                l_cap=256, kl_cap=62, t_cap=16, pt_cap=16, s_cap=3,
                sg_cap=16, asg_cap=16)
    t0 = time.monotonic()
    summary, stats = run_named_workload(cfg, tpu=True, caps=caps,
                                        batch_size=BATCH)
    wall = time.monotonic() - t0
    if not stats.get("barrier_ok", False):
        return {"error": "pods left unscheduled", "value": 0.0,
                "detail": summary.to_dict()}
    detail = summary.to_dict()
    e2e = stats.get("e2e") or {}
    if e2e:
        detail["pod_e2e_p50_ms"] = e2e.get("p50_ms")
        detail["pod_e2e_p99_ms"] = e2e.get("p99_ms")
    return {"value": summary.average, "wall_s": round(wall, 1),
            "detail": detail}


def emit(value: float, extra: dict) -> None:
    print(json.dumps({
        "metric": "scheduler_perf_throughput",
        "value": round(value, 1),
        "unit": "pods/s",
        "vs_baseline": round(value / BASELINE_PODS_PER_SEC, 2),
        "detail": {"nodes": N_NODES, "pods": N_PODS, "batch": BATCH,
                   **extra},
    }))


def main() -> None:
    n_runs = max(1, int(os.environ.get("BENCH_RUNS", "3")))
    if os.environ.get("_BENCH_CHILD") == "1" or n_runs == 1:
        res = run_once()
        if "error" in res:
            emit(0.0, {"error": res["error"], **res["detail"]})
            sys.exit(1)
        emit(res["value"], {"wall_s": res["wall_s"], **res["detail"]})
        return

    t0 = time.monotonic()
    results: list[dict] = []
    env = dict(os.environ, _BENCH_CHILD="1")
    for _ in range(n_runs):
        for attempt in (1, 2):  # one retry: tunnel hiccups are transient
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            if proc.returncode == 0:
                results.append(
                    json.loads(proc.stdout.strip().splitlines()[-1]))
                break
            sys.stderr.write(proc.stderr[-2000:])
        else:
            # relay the child's own JSON (e.g. "pods left unscheduled")
            # so the driver's one line carries the real failure
            lines = proc.stdout.strip().splitlines()
            if lines:
                try:
                    child = json.loads(lines[-1])
                    emit(0.0, child.get("detail", {"error": "child failed"}))
                    sys.exit(1)
                except json.JSONDecodeError:
                    pass
            emit(0.0, {"error": "bench child failed twice"})
            sys.exit(1)
    wall = time.monotonic() - t0
    results.sort(key=lambda r: r["value"])
    med = results[len(results) // 2]
    emit(med["value"], {"wall_s": round(wall, 1), "runs": n_runs,
                        "averages": [r["value"] for r in results],
                        **{k: v for k, v in med["detail"].items()
                           if k not in ("nodes", "pods", "batch",
                                        "wall_s")}})


if __name__ == "__main__":
    main()
