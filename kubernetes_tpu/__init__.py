"""kubernetes_tpu — a TPU-native container-orchestration control plane.

A brand-new framework with the capabilities of Kubernetes (reference:
AndreKapraty/kubernetes, ~v1.26), re-designed TPU-first: the control plane
(store, API server, informers, controllers) is classic systems code, while the
scheduler's Filter/Score/Assign hot path is a batched JAX/XLA program that
schedules MANY pods per step on TPU instead of one pod per loop iteration.

Package map (see SURVEY.md for the reference analysis this is built to):
  api/         - object model: Pod/Node/..., quantities, label selectors
                 (reference: staging/src/k8s.io/api + apimachinery)
  store/       - versioned in-memory MVCC store with watch
                 (reference: etcd + staging/src/k8s.io/apiserver/pkg/storage)
  apiserver/   - REST+watch server over the store
  client/      - reflector / informer / lister / workqueue / leader election
                 (reference: staging/src/k8s.io/client-go)
  scheduler/   - queue, cache, framework extension points, pure-python plugins
                 (reference: pkg/scheduler)
  ops/         - snapshot->tensor flattener, vmapped predicates/scores, kernels
  models/      - batched assignment solvers (greedy, auction/sinkhorn)
  parallel/    - device mesh + shard_map sharding of the node axis
  controllers/ - replicaset/deployment/... reconcilers (reference: pkg/controller)
  kubelet/     - hollow node agent (reference: pkg/kubelet + kubemark)
  proxy/       - service->endpoint dataplane simulation (reference: pkg/proxy)
  cli/         - kubectl-equivalent CLI
"""

__version__ = "0.1.0"
