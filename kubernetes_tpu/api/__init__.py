"""Object model: JSON-shaped objects, quantities, labels, resource accounting."""

from . import labels, meta, quantity, resources  # noqa: F401
from .meta import Obj  # noqa: F401
