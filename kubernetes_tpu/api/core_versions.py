"""Multi-version serving for CORE API types (hub-and-spoke conversion).

Reference: pkg/apis/core/v1/conversion.go + defaults.go and
apimachinery/pkg/runtime/scheme.go — the reference converts every core
object between its internal hub type and the served v1 on each request,
which is what makes versioned evolution / rolling upgrades possible.
Here the stored v1 form IS the hub, and additional served versions
declare a pair of pure conversion functions to/from it, exactly the
seam CRDs use (apiserver/crd.py convert/to_storage) but for built-ins.

The served v2alpha1 Pod regroups the scheduling knobs that v1 scatters
across spec/status into one `spec.scheduling` stanza:

    v1                              v2alpha1
    spec.schedulerName          ->  spec.scheduling.schedulerName
    spec.priority               ->  spec.scheduling.priority
    spec.priorityClassName      ->  spec.scheduling.priorityClassName
    spec.preemptionPolicy       ->  spec.scheduling.preemptionPolicy
    status.nominatedNodeName    ->  status.scheduling.nominatedNodeName

Everything else passes through untouched (unknown fields survive the
round trip in both directions).  v2alpha1 defaulting fills
scheduling.schedulerName="default-scheduler", mirroring v1's
SetDefaults_PodSpec schedulerName default.
"""

from __future__ import annotations

HUB = "v1"
SERVED_VERSIONS = ("v1", "v2alpha1")

_SPEC_FIELDS = ("schedulerName", "priority", "priorityClassName",
                "preemptionPolicy")


def _pod_to_v2alpha1(pod: dict) -> dict:
    out = dict(pod)
    out["apiVersion"] = "v2alpha1"
    spec = dict(pod.get("spec") or {})
    sched = dict(spec.pop("scheduling", None) or {})
    for f in _SPEC_FIELDS:
        if f in spec:
            sched[f] = spec.pop(f)
    if sched:
        spec["scheduling"] = sched
    out["spec"] = spec
    status = pod.get("status")
    if status and "nominatedNodeName" in status:
        status = dict(status)
        st_sched = dict(status.get("scheduling") or {})
        st_sched["nominatedNodeName"] = status.pop("nominatedNodeName")
        status["scheduling"] = st_sched
        out["status"] = status
    return out


def _pod_to_v1(pod: dict) -> dict:
    out = dict(pod)
    out["apiVersion"] = "v1"
    spec = dict(pod.get("spec") or {})
    sched = spec.pop("scheduling", None)
    if sched:
        for f in _SPEC_FIELDS:
            if f in sched:
                spec[f] = sched[f]
        extra = {k: v for k, v in sched.items() if k not in _SPEC_FIELDS}
        if extra:
            spec["scheduling"] = extra  # unknown subfields survive
    out["spec"] = spec
    status = pod.get("status")
    if status and "scheduling" in status:
        status = dict(status)
        st_sched = dict(status["scheduling"])
        if "nominatedNodeName" in st_sched:
            status["nominatedNodeName"] = st_sched.pop("nominatedNodeName")
        if st_sched:
            status["scheduling"] = st_sched
        else:
            status.pop("scheduling")
        out["status"] = status
    return out


def _pod_default_v2alpha1(pod: dict) -> dict:
    spec = pod.get("spec")
    if spec is None:
        return pod
    sched = spec.get("scheduling")
    if sched is None or sched.get("schedulerName") in (None, ""):
        pod = dict(pod)
        spec = dict(spec)
        sched = dict(sched or {})
        sched["schedulerName"] = "default-scheduler"
        spec["scheduling"] = sched
        pod["spec"] = spec
    return pod


# resource -> version -> (from_hub, to_hub, default_or_None)
_CONVERTERS: dict[str, dict[str, tuple]] = {
    "pods": {
        "v2alpha1": (_pod_to_v2alpha1, _pod_to_v1, _pod_default_v2alpha1),
    },
}


# ---- v1 (hub) write-time defaulting ---------------------------------------
# pkg/apis/core/v1/defaults.go — the load-bearing defaults every
# reference client may assume are present on a stored object.  All
# functions MUTATE in place and only fill MISSING fields (idempotent),
# so re-running on updates/patches can never clobber user intent.

_VOLUME_MODE_RESOURCES = ("persistentvolumes", "persistentvolumeclaims")


def _default_container(c: dict) -> None:
    """SetDefaults_Container (defaults.go): pull policy by image tag,
    termination message fields, port protocol, probe timings."""
    if not c.get("imagePullPolicy"):
        image = c.get("image") or ""
        tag = image.rpartition(":")[2] if ":" in image.rpartition("/")[2] \
            else ""
        c["imagePullPolicy"] = ("Always" if tag in ("", "latest")
                                else "IfNotPresent")
    c.setdefault("terminationMessagePath", "/dev/termination-log")
    c.setdefault("terminationMessagePolicy", "File")
    for p in c.get("ports") or ():
        p.setdefault("protocol", "TCP")
    for probe_key in ("livenessProbe", "readinessProbe", "startupProbe"):
        probe = c.get(probe_key)
        if probe is not None:
            probe.setdefault("timeoutSeconds", 1)
            probe.setdefault("periodSeconds", 10)
            probe.setdefault("successThreshold", 1)
            probe.setdefault("failureThreshold", 3)
            if "httpGet" in probe:
                probe["httpGet"].setdefault("scheme", "HTTP")


def _default_pod_v1(pod: dict) -> None:
    """SetDefaults_Pod/PodSpec (defaults.go:118-199)."""
    spec = pod.setdefault("spec", {})
    spec.setdefault("restartPolicy", "Always")
    spec.setdefault("dnsPolicy", "ClusterFirst")
    spec.setdefault("schedulerName", "default-scheduler")
    spec.setdefault("terminationGracePeriodSeconds", 30)
    spec.setdefault("enableServiceLinks", True)
    spec.setdefault("securityContext", {})
    all_containers = list(spec.get("containers") or ()) + list(
        spec.get("initContainers") or ())
    for c in all_containers:
        _default_container(c)
    if spec.get("hostNetwork"):
        # hostNetwork ports bind the node: hostPort defaults to
        # containerPort, for init containers too (defaults.go
        # SetDefaults_Pod defaultHostNetworkPorts on both lists)
        for c in all_containers:
            for p in c.get("ports") or ():
                if p.get("containerPort") and not p.get("hostPort"):
                    p["hostPort"] = p["containerPort"]
    for v in spec.get("volumes") or ():
        # volume-source mode defaults (0644 == 420 decimal)
        for key in ("secret", "configMap", "downwardAPI", "projected"):
            if key in v and isinstance(v[key], dict):
                v[key].setdefault("defaultMode", 420)
        if "hostPath" in v and isinstance(v["hostPath"], dict):
            v["hostPath"].setdefault("type", "")


def _default_service_v1(svc: dict) -> None:
    """SetDefaults_Service (defaults.go:80-117)."""
    spec = svc.setdefault("spec", {})
    spec.setdefault("sessionAffinity", "None")
    spec.setdefault("type", "ClusterIP")
    if spec["sessionAffinity"] == "ClientIP":
        cfg = spec.setdefault("sessionAffinityConfig", {})
        cfg.setdefault("clientIP", {}).setdefault("timeoutSeconds", 10800)
    for p in spec.get("ports") or ():
        p.setdefault("protocol", "TCP")
        if "targetPort" not in p and "port" in p:
            p["targetPort"] = p["port"]
    if spec["type"] in ("NodePort", "LoadBalancer"):
        spec.setdefault("externalTrafficPolicy", "Cluster")
    spec.setdefault("internalTrafficPolicy", "Cluster")


def _default_node_v1(node: dict) -> None:
    """SetDefaults_NodeStatus: allocatable mirrors capacity when unset."""
    status = node.get("status")
    if status and status.get("capacity") and not status.get("allocatable"):
        status["allocatable"] = dict(status["capacity"])


def _default_pv_v1(pv: dict) -> None:
    spec = pv.setdefault("spec", {})
    spec.setdefault("persistentVolumeReclaimPolicy", "Retain")
    spec.setdefault("volumeMode", "Filesystem")
    pv.setdefault("status", {}).setdefault("phase", "Pending")


def _default_pvc_v1(pvc: dict) -> None:
    pvc.setdefault("spec", {}).setdefault("volumeMode", "Filesystem")
    pvc.setdefault("status", {}).setdefault("phase", "Pending")


def _default_secret_v1(secret: dict) -> None:
    secret.setdefault("type", "Opaque")


def _default_namespace_v1(ns: dict) -> None:
    ns.setdefault("status", {}).setdefault("phase", "Active")


def _default_endpoints_v1(ep: dict) -> None:
    for subset in ep.get("subsets") or ():
        for p in subset.get("ports") or ():
            p.setdefault("protocol", "TCP")


_V1_DEFAULTERS = {
    "pods": _default_pod_v1,
    "services": _default_service_v1,
    "nodes": _default_node_v1,
    "persistentvolumes": _default_pv_v1,
    "persistentvolumeclaims": _default_pvc_v1,
    "secrets": _default_secret_v1,
    "namespaces": _default_namespace_v1,
    "endpoints": _default_endpoints_v1,
}


def default_v1(resource: str, obj: dict) -> dict:
    """Apply v1 write-time defaulting in place and return obj (the
    apiserver's write pipeline calls this for every core hub-form
    write; defaults.go runs at decode the same way).  Unknown resources
    pass through."""
    fn = _V1_DEFAULTERS.get(resource)
    if fn is not None and isinstance(obj, dict):
        fn(obj)
    return obj


def handles(resource: str, version: str) -> bool:
    """Is `resource` served at non-hub `version`?"""
    return version in _CONVERTERS.get(resource, ())


def convert(resource: str, obj: dict, target_version: str,
            default: bool = True) -> dict:
    """Serve a stored (hub-form) object at target_version; hub target is
    the identity.  Pure: never mutates the input.

    default=False gives conversion WITHOUT the served version's
    defaulting — for internal round trips (SSA merge, patch-base
    conversion) where injected defaults would masquerade as user-written
    fields."""
    if target_version == HUB:
        return obj
    entry = _CONVERTERS.get(resource, {}).get(target_version)
    if entry is None:
        return obj
    from_hub, _to_hub, defaulter = entry
    out = from_hub(obj)
    if default and defaulter is not None:
        out = defaulter(out)
    return out


def convert_many(resource: str, objs: list[dict],
                 target_version: str) -> list[dict]:
    if target_version == HUB or not handles(resource, target_version):
        return objs
    return [convert(resource, o, target_version) for o in objs]


def to_storage(resource: str, obj: dict, from_version: str,
               default: bool = True) -> dict:
    """A request body written at from_version -> the stored hub form.
    Per-version defaulting runs BEFORE conversion (the reference defaults
    in the served version's types, then converts to the hub); pass
    default=False on internal conversions that must not invent fields."""
    if from_version == HUB:
        return obj
    entry = _CONVERTERS.get(resource, {}).get(from_version)
    if entry is None:
        return obj
    _from_hub, to_hub, defaulter = entry
    if default and defaulter is not None:
        obj = defaulter(obj)
    return to_hub(obj)
