"""Field selectors (apimachinery/pkg/fields): comma-joined dotted-path
equality terms — `spec.nodeName=X`, `metadata.name!=y`, `a.b==c`.

The load-bearing consumer is the reference kubelet's
spec.nodeName=<node> pod watch (pkg/kubelet/config/apiserver.go:38).
Shared by the apiserver (list/watch fieldSelector params) and kubectl
(--field-selector), so the two sides cannot drift.
"""

from __future__ import annotations


def field_of(obj: dict, dotted: str):
    """Dotted-path read ('spec.nodeName' -> obj['spec']['nodeName']);
    None when any hop is missing."""
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _term_value(obj: dict, path: str) -> str:
    v = field_of(obj, path.strip())
    # absent compares as '' — but present falsy values (0, False) must
    # keep their string form, so no `or ""` coercion
    return "" if v is None else str(v)


def matches_field_selector(obj: dict, selector: str) -> bool:
    """True when obj satisfies every term.  Raises ValueError on a
    malformed selector (a term with no operator) — the reference
    apiserver answers 400 'invalid field selector', never
    silently-match-everything."""
    for part in selector.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            if _term_value(obj, k) == v.strip():
                return False
        elif "=" in part:
            k, _, v = part.partition("=")
            if _term_value(obj, k) != v.lstrip("=").strip():
                return False
        else:
            raise ValueError(f"invalid field selector term {part!r}")
    return True


def validate_field_selector(selector: str) -> None:
    """Raise ValueError for malformed selectors (probe with an empty
    object; only syntax matters)."""
    matches_field_selector({}, selector)
