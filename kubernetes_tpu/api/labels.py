"""Label sets and selectors.

Reference semantics: staging/src/k8s.io/apimachinery/pkg/labels/selector.go
(operators In/NotIn/Exists/DoesNotExist/Gt/Lt) and
pkg/apis/meta/v1 LabelSelector (matchLabels + matchExpressions), converted via
LabelSelectorAsSelector (apimachinery/pkg/apis/meta/v1/helpers.go).

A selector is compiled once into a list of requirement tuples and evaluated
against plain dict label sets.  The TPU flattener further compiles selectors
into hashed-vocabulary integer arrays (ops/flatten.py); this module is the
scalar truth.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable

# Operator constants mirror metav1.LabelSelectorOperator / selection.Operator.
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"


@dataclass(frozen=True, slots=True)
class Requirement:
    key: str
    operator: str
    values: tuple[str, ...] = ()

    def matches(self, labels: dict[str, str]) -> bool:
        has = self.key in labels
        if self.operator == EXISTS:
            return has
        if self.operator == DOES_NOT_EXIST:
            return not has
        if self.operator == IN:
            return has and labels[self.key] in self.values
        if self.operator == NOT_IN:
            # NotIn matches when the key is absent OR value not in set
            # (matches reference labels.Requirement.Matches).
            return not has or labels[self.key] not in self.values
        if self.operator in (GT, LT):
            if not has:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if self.operator == GT else lhs < rhs
        raise ValueError(f"unknown operator {self.operator!r}")


@dataclass(frozen=True, slots=True)
class Selector:
    """Compiled selector: conjunction of requirements. Empty selects everything."""

    requirements: tuple[Requirement, ...] = ()
    # A LabelSelector of `None` in the API means "match nothing"; we encode that
    # with match_nothing=True (reference: LabelSelectorAsSelector(nil) -> Nothing()).
    match_nothing: bool = False

    def matches(self, labels: dict[str, str] | None) -> bool:
        if self.match_nothing:
            return False
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    def is_empty(self) -> bool:
        return not self.match_nothing and not self.requirements


EVERYTHING = Selector()
NOTHING = Selector(match_nothing=True)


def parse_selector(text: str) -> Selector:
    """String selector -> Selector (labels.Parse subset): comma-joined
    requirements of the forms `k=v`/`k==v`, `k!=v`, `k`, `!k`,
    `k in (a,b)`, `k notin (a,b)`, `k > n`, `k < n`.  This keeps the
    CLI's -l flag on the same Requirement semantics as everything else
    (NotIn matches absent keys, etc.)."""
    import re

    set_re = re.compile(
        r"^\s*(?P<key>[^\s!=<>,()]+)\s+(?P<op>in|notin)\s*"
        r"\(\s*(?P<vals>[^()]*)\)\s*$")
    reqs: list[Requirement] = []
    # split on commas NOT inside parentheses (set expressions)
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    for part in parts:
        part = part.strip()
        if not part:
            continue
        m = set_re.match(part)
        if m:
            values = tuple(v.strip() for v in m.group("vals").split(",")
                           if v.strip())
            reqs.append(Requirement(
                m.group("key"), IN if m.group("op") == "in" else NOT_IN,
                values))
            continue
        if "!=" in part:
            key, _, value = part.partition("!=")
            reqs.append(Requirement(key.strip(), NOT_IN,
                                    (value.strip(),)))
        elif "==" in part or "=" in part:
            key, _, value = part.partition("==" if "==" in part else "=")
            reqs.append(Requirement(key.strip(), IN, (value.strip(),)))
        elif ">" in part:
            key, _, value = part.partition(">")
            reqs.append(Requirement(key.strip(), GT, (value.strip(),)))
        elif "<" in part:
            key, _, value = part.partition("<")
            reqs.append(Requirement(key.strip(), LT, (value.strip(),)))
        elif part.startswith("!"):
            reqs.append(Requirement(part[1:].strip(), DOES_NOT_EXIST))
        else:
            reqs.append(Requirement(part, EXISTS))
    return Selector(tuple(reqs))


def selector_from_dict(spec: dict | None) -> Selector:
    """Compile a metav1.LabelSelector JSON dict into a Selector.

    None -> NOTHING; {} -> EVERYTHING (matches reference helpers.go semantics).
    """
    if spec is None:
        return NOTHING
    reqs: list[Requirement] = []
    for k, v in sorted((spec.get("matchLabels") or {}).items()):
        reqs.append(Requirement(k, IN, (v,)))
    for expr in spec.get("matchExpressions") or ():
        op = expr["operator"]
        values = tuple(expr.get("values") or ())
        reqs.append(Requirement(expr["key"], op, values))
    return Selector(tuple(reqs))


def selector_from_match_labels(match_labels: dict[str, str] | None) -> Selector:
    if match_labels is None:
        return NOTHING
    return Selector(tuple(Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items())))
