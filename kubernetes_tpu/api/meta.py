"""Object metadata helpers.

Objects throughout the framework are plain JSON-shaped dicts — the same shape
the reference serves on the wire (staging/src/k8s.io/api types serialized via
apimachinery codecs).  We deliberately do NOT build a parallel dataclass
hierarchy: the store, watch, informers and REST layer all deal in serialized
objects, and at 100k-node/1M-pod bench scale dict objects are materially
cheaper to create/copy than nested dataclasses.

This module is the accessor layer (the moral equivalent of
apimachinery/pkg/apis/meta/v1 ObjectMeta + meta.Accessor).
"""

from __future__ import annotations

import copy
import itertools
import time
import uuid
from typing import Any

Obj = dict[str, Any]

# deletion-propagation finalizers (apimachinery metav1 FinalizerDeleteDependents
# / FinalizerOrphanDependents; processed by the garbage collector)
FOREGROUND_FINALIZER = "foregroundDeletion"
ORPHAN_FINALIZER = "orphan"


def propagation_finalizer(policy: str | None) -> str | None:
    """DeleteOptions.propagationPolicy -> finalizer to park the object
    with (None for Background/default: delete immediately, GC cascades)."""
    if policy == "Foreground":
        return FOREGROUND_FINALIZER
    if policy == "Orphan":
        return ORPHAN_FINALIZER
    return None


def new_object(kind: str, name: str, namespace: str | None = "default", **meta: Any) -> Obj:
    o: Obj = {"apiVersion": "v1", "kind": kind, "metadata": {"name": name}}
    if namespace is not None:
        o["metadata"]["namespace"] = namespace
    o["metadata"].update(meta)
    return o


def name(o: Obj) -> str:
    return o["metadata"]["name"]


def namespace(o: Obj) -> str:
    return o["metadata"].get("namespace", "")


def namespaced_name(o: Obj) -> str:
    """'ns/name' key — the reference's types.NamespacedName / cache.MetaNamespaceKeyFunc."""
    ns = namespace(o)
    return f"{ns}/{name(o)}" if ns else name(o)


def uid(o: Obj) -> str:
    return o["metadata"].get("uid", "")


def resource_version(o: Obj) -> int:
    rv = o["metadata"].get("resourceVersion", 0)
    return int(rv)


def set_resource_version(o: Obj, rv: int) -> None:
    o["metadata"]["resourceVersion"] = rv


def labels(o: Obj) -> dict[str, str]:
    return o["metadata"].get("labels") or {}


def annotations(o: Obj) -> dict[str, str]:
    return o["metadata"].get("annotations") or {}


def creation_timestamp(o: Obj) -> float:
    return o["metadata"].get("creationTimestamp", 0.0)


def deletion_timestamp(o: Obj) -> float | None:
    return o["metadata"].get("deletionTimestamp")


def owner_references(o: Obj) -> list[Obj]:
    return o["metadata"].get("ownerReferences") or []


def controller_ref(o: Obj) -> Obj | None:
    """The owning controller reference (metav1.GetControllerOf)."""
    for ref in owner_references(o):
        if ref.get("controller"):
            return ref
    return None


# uid generation: a random per-process prefix plus a counter.  uuid.uuid4()
# costs ~36us each, which at bench scale (one uid per object create, events
# included) shows up in end-to-end throughput; uniqueness is what the uid
# contract needs (apimachinery types.UID), not crypto randomness.
_uid_prefix = uuid.uuid4().hex[:12]
_uid_counter = itertools.count(1)


def new_uid() -> str:
    """Next unique object uid (bulk-create hot path)."""
    return f"{_uid_prefix}-{next(_uid_counter):09x}"


def finalize_new(o: Obj) -> None:
    """Fill in server-side metadata on create (uid, creationTimestamp)."""
    md = o["metadata"]
    if not md.get("uid"):
        md["uid"] = new_uid()
    if not md.get("creationTimestamp"):
        md["creationTimestamp"] = time.time()


def deep_copy(o: Obj) -> Obj:
    """Deep copy an object tree. Uses the native fastcopy extension when
    built (native/fastcopy, ~10x faster on the store write path)."""
    from ..utils.fastcopy import deep_copy_json
    return deep_copy_json(o)


def pod_is_terminal(pod: Obj) -> bool:
    phase = (pod.get("status") or {}).get("phase")
    return phase in ("Succeeded", "Failed")


def pod_node_name(pod: Obj) -> str:
    return (pod.get("spec") or {}).get("nodeName", "") or ""
