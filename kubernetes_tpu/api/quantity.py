"""Resource quantity parsing/formatting.

Reference semantics: staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go
(suffix grammar at suffix.go) — decimal SI (n, u, m, "", k, M, G, T, P, E) and
binary (Ki, Mi, Gi, Ti, Pi, Ei) suffixes, plus scientific notation.

The scheduler never works with arbitrary-precision quantities: like the
reference's framework.Resource (pkg/scheduler/framework/types.go:426), we
canonicalize at the edge:
  cpu               -> integer millicores  (parse_cpu_milli)
  memory/storage    -> integer bytes       (parse_mem_bytes)
  everything else   -> integer base units
so the TPU flattener only ever sees int64/float32 arrays.
"""

from __future__ import annotations

import functools
import re

_BIN = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DEC = {
    "n": 10**-9, "u": 10**-6, "m": 10**-3, "": 1,
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18,
}

_QTY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<suffix>[numkMGTPE]|[KMGTPE]i)|[eE](?P<exp>[+-]?\d+))?$"
)


def parse_quantity(s: str | int | float) -> float:
    """Parse a Kubernetes quantity string into a float of base units.
    Cached: workloads reuse a handful of distinct quantity strings, and
    this sits on the PodInfo hot path."""
    if isinstance(s, (int, float)):
        return float(s)
    return _parse_quantity_str(s)


@functools.lru_cache(maxsize=4096)
def _parse_quantity_str(s: str) -> float:
    s = s.strip()
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    value = float(m.group("num"))
    if m.group("sign") == "-":
        value = -value
    suffix = m.group("suffix")
    if suffix:
        value *= _BIN[suffix] if suffix in _BIN else _DEC[suffix]
    elif m.group("exp") is not None:
        value *= 10.0 ** int(m.group("exp"))
    return value


def parse_cpu_milli(s: str | int | float) -> int:
    """CPU quantity -> integer millicores ("100m" -> 100, "2" -> 2000)."""
    return round(parse_quantity(s) * 1000)


def parse_mem_bytes(s: str | int | float) -> int:
    """Memory/storage quantity -> integer bytes ("64Mi" -> 67108864)."""
    return round(parse_quantity(s))


def format_cpu_milli(milli: int) -> str:
    if milli % 1000 == 0:
        return str(milli // 1000)
    return f"{milli}m"


def format_mem_bytes(n: int) -> str:
    for suf in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
        d = _BIN[suf]
        if n >= d and n % d == 0:
            return f"{n // d}{suf}"
    return str(n)
