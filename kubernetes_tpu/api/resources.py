"""Pod/Node resource accounting.

Reference semantics:
  pkg/scheduler/framework/types.go:426  (Resource: MilliCPU/Memory/
    EphemeralStorage/AllowedPodNumber/ScalarResources)
  pkg/scheduler/framework/plugins/noderesources/fit.go:160
    (computePodResourceRequest: sum containers, max with initContainers,
     add pod overhead)
  pkg/api/v1/pod util + scheduler GetNonzeroRequests (non-zero defaults:
    100m CPU / 200Mi memory for pods that request nothing, used only by
    scoring so empty pods still spread).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any

from .quantity import parse_cpu_milli, parse_mem_bytes, parse_quantity

# Well-known resource names (reference: v1.ResourceCPU etc.)
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

# Scoring defaults for pods with no requests
# (reference: pkg/scheduler/util/non_zero.go DefaultMilliCPURequest/DefaultMemoryRequest).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024


@dataclass(slots=True)
class Resource:
    """Canonical integer resource vector (framework/types.go:426)."""

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar: dict[str, float] = field(default_factory=dict)

    def add(self, other: "Resource") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) + v

    def sub(self, other: "Resource") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) - v

    def set_max(self, other: "Resource") -> None:
        self.milli_cpu = max(self.milli_cpu, other.milli_cpu)
        self.memory = max(self.memory, other.memory)
        self.ephemeral_storage = max(self.ephemeral_storage, other.ephemeral_storage)
        for k, v in other.scalar.items():
            self.scalar[k] = max(self.scalar.get(k, 0), v)

    def clone(self) -> "Resource":
        return Resource(self.milli_cpu, self.memory, self.ephemeral_storage,
                        self.allowed_pod_number, dict(self.scalar))


def _parse_resource_list(rl: dict[str, Any] | None) -> Resource:
    """Cached on the (sorted items) tuple: benchmark/real workloads repeat a
    small set of request shapes, and this is the PodInfo hot path."""
    if not rl:
        return Resource()
    try:
        # lru_cache hashes the key, so unhashable VALUES raise there —
        # keep the cached call inside the try
        return _parse_resource_list_cached(tuple(sorted(rl.items()))).clone()
    except TypeError:
        return _parse_resource_list_uncached(rl)


@functools.lru_cache(maxsize=4096)
def _parse_resource_list_cached(items: tuple) -> Resource:
    return _parse_resource_list_uncached(dict(items))


def _parse_resource_list_uncached(rl: dict[str, Any]) -> Resource:
    r = Resource()
    for k, v in (rl or {}).items():
        if k == CPU:
            r.milli_cpu = parse_cpu_milli(v)
        elif k == MEMORY:
            r.memory = parse_mem_bytes(v)
        elif k == EPHEMERAL_STORAGE:
            r.ephemeral_storage = parse_mem_bytes(v)
        elif k == PODS:
            r.allowed_pod_number = int(parse_quantity(v))
        else:
            r.scalar[k] = parse_quantity(v)
    return r


@functools.lru_cache(maxsize=4096)
def _request_pair_cached(key: tuple) -> tuple[Resource, Resource]:
    """(request, request_nonzero) as SHARED FROZEN instances for a
    single-container requests shape.  Callers must treat both as
    immutable (consumers only read them: NodeInfo add/sub read `other`,
    plugins and the flattener only read fields)."""
    r = _parse_resource_list_uncached(dict(key))
    nz = r.clone()
    if nz.milli_cpu == 0:
        nz.milli_cpu = DEFAULT_MILLI_CPU_REQUEST
    if nz.memory == 0:
        nz.memory = DEFAULT_MEMORY_REQUEST
    return r, nz


def request_pair_from_requests(rl: dict | None) -> tuple[Resource, Resource]:
    """(request, request_nonzero) straight from a single-container
    requests dict — the native pod_scan fast path's entry (the scan
    already proved the pod has exactly one container, no initContainers,
    no overhead).  Same shared-frozen-instance contract as
    pod_request_pair."""
    try:
        return _request_pair_cached(tuple(sorted(rl.items())) if rl else ())
    except (TypeError, AttributeError):  # unhashable/malformed: private
        r = _parse_resource_list_uncached(rl if isinstance(rl, dict) else {})
        return r, pod_request_nonzero(None, r)


def pod_request_pair(pod: dict) -> tuple[Resource, Resource]:
    """(pod_request, pod_request_nonzero) with a shared-instance fast path
    for the dominant pod shape (one container, no initContainers, no
    overhead).  The returned Resources are SHARED and must not be mutated;
    pods outside the fast shape get private instances."""
    spec = pod.get("spec") or {}
    containers = spec.get("containers") or ()
    if (len(containers) == 1 and not spec.get("initContainers")
            and not spec.get("overhead")):
        rl = (containers[0].get("resources") or {}).get("requests")
        try:
            # the lru_cache HASHES the key, so the unhashable-value
            # TypeError surfaces there — the call must sit inside the try
            return _request_pair_cached(
                tuple(sorted(rl.items())) if rl else ())
        except TypeError:
            pass  # unhashable values: fall through to the general path
    r = pod_request(pod)
    return r, pod_request_nonzero(pod, r)


def pod_request(pod: dict) -> Resource:
    """computePodResourceRequest (noderesources/fit.go:160): sum of container
    requests, component-wise max with each initContainer, plus pod overhead."""
    spec = pod.get("spec") or {}
    total = Resource()
    for c in spec.get("containers") or ():
        total.add(_parse_resource_list((c.get("resources") or {}).get("requests")))
    for c in spec.get("initContainers") or ():
        total.set_max(_parse_resource_list((c.get("resources") or {}).get("requests")))
    if spec.get("overhead"):
        total.add(_parse_resource_list(spec["overhead"]))
    return total


def pod_request_nonzero(pod: dict, request: Resource | None = None) -> Resource:
    """Like pod_request but with scoring defaults applied (non_zero.go).
    Pass an already-computed pod_request to skip the re-parse (PodInfo hot
    path computes both)."""
    r = request.clone() if request is not None else pod_request(pod)
    if r.milli_cpu == 0:
        r.milli_cpu = DEFAULT_MILLI_CPU_REQUEST
    if r.memory == 0:
        r.memory = DEFAULT_MEMORY_REQUEST
    return r


def node_allocatable(node: dict) -> Resource:
    status = node.get("status") or {}
    rl = status.get("allocatable") or status.get("capacity")
    r = _parse_resource_list(rl)
    if r.allowed_pod_number == 0:
        r.allowed_pod_number = 110  # kubelet default max-pods
    return r


def make_resource_list(cpu_milli: int = 0, mem: int = 0, pods: int = 110,
                       ephemeral: int = 0, **scalar: float) -> dict[str, str]:
    """Convenience builder for node capacity/allocatable dicts (tests/benches)."""
    rl = {CPU: f"{cpu_milli}m", MEMORY: str(mem), PODS: str(pods)}
    if ephemeral:
        rl[EPHEMERAL_STORAGE] = str(ephemeral)
    for k, v in scalar.items():
        rl[k] = str(v)
    return rl
