"""REST+watch API server (reference: kube-apiserver serving stack)."""

from .server import AdmissionError, APIServer, status_error  # noqa: F401
