"""Admission control chain.

Reference: staging/src/k8s.io/apiserver/pkg/admission (two-phase chain:
all mutating plugins run before all validating plugins; order fixed by
pkg/kubeapiserver/options/plugins.go:64) + in-tree plugins under
plugin/pkg/admission/ + webhook admission
(staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook — AdmissionReview
POSTed to external HTTP endpoints, mutating webhooks may return a JSONPatch).

In-tree set reproduced (the ones meaningful without kubelet-side state):
  NamespaceLifecycle    reject writes into missing/terminating namespaces
  Priority              resolve priorityClassName -> spec.priority
  LimitRanger           apply LimitRange defaults to container resources
  ResourceQuota         reject creates that would exceed a ResourceQuota
  DefaultTolerationSeconds  add default NoExecute tolerations to pods
  TaintNodesByCondition vestigial here (node controller owns taints)
"""

from __future__ import annotations

import json
import logging
import threading
import time as _time
import urllib.request
from typing import Callable, List, Optional

from ..api import meta, quantity
from ..store import kv
from . import patch as patchlib

logger = logging.getLogger(__name__)

CREATE, UPDATE, DELETE, CONNECT = "CREATE", "UPDATE", "DELETE", "CONNECT"


class AdmissionDenied(Exception):
    """Rejection: surfaces as HTTP 400/403 with the plugin name."""

    def __init__(self, plugin: str, message: str):
        super().__init__(message)
        self.plugin = plugin


class Attributes:
    """admission.Attributes (pkg/admission/interfaces.go)."""

    __slots__ = ("verb", "resource", "subresource", "namespace", "name",
                 "obj", "old_obj")

    def __init__(self, verb: str, resource: str, obj, old_obj=None,
                 namespace: str = "", name: str = "", subresource: str = ""):
        self.verb = verb
        self.resource = resource
        self.subresource = subresource
        self.namespace = namespace
        self.name = name
        self.obj = obj
        self.old_obj = old_obj


class AdmissionPlugin:
    name = "plugin"

    def admit(self, attrs: Attributes) -> None:
        """Mutating phase: may modify attrs.obj in place or raise."""

    def validate(self, attrs: Attributes) -> None:
        """Validating phase: raise AdmissionDenied to reject."""


class Chain:
    """Runs every plugin's admit(), then every plugin's validate()."""

    def __init__(self, plugins: Optional[List[AdmissionPlugin]] = None):
        self.plugins: List[AdmissionPlugin] = list(plugins or ())

    def register(self, plugin: AdmissionPlugin) -> None:
        self.plugins.append(plugin)

    def run(self, attrs: Attributes) -> None:
        for p in self.plugins:
            p.admit(attrs)
        for p in self.plugins:
            p.validate(attrs)


# -- in-tree plugins -------------------------------------------------------

class NamespaceLifecycle(AdmissionPlugin):
    """plugin/pkg/admission/namespace/lifecycle: creates into a
    nonexistent or terminating namespace are rejected; the immortal
    namespaces (default, kube-system) can't be deleted."""

    name = "NamespaceLifecycle"
    IMMORTAL = {"default", "kube-system", "kube-public"}

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource == "namespaces" and attrs.verb == DELETE:
            if attrs.name in self.IMMORTAL:
                raise AdmissionDenied(self.name,
                                      "this namespace may not be deleted")
            return
        if attrs.verb != CREATE or not attrs.namespace:
            return
        if attrs.resource in ("namespaces", "events"):
            return
        try:
            ns = self.store.get("namespaces", "", attrs.namespace)
        except kv.NotFoundError:
            if attrs.namespace == "default":
                return  # default namespace is implicit
            raise AdmissionDenied(
                self.name, "namespace %r not found" % attrs.namespace)
        phase = ((ns.get("status") or {}).get("phase")
                 or ("Terminating" if meta.deletion_timestamp(ns) else "Active"))
        if phase == "Terminating":
            raise AdmissionDenied(
                self.name,
                "unable to create new content in namespace %s because it is "
                "being terminated" % attrs.namespace)


class Priority(AdmissionPlugin):
    """plugin/pkg/admission/priority: resolve pod.spec.priorityClassName to
    spec.priority; unknown class -> reject; default class applies."""

    name = "Priority"

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        pod = attrs.obj
        spec = pod.setdefault("spec", {})
        cls_name = spec.get("priorityClassName")
        if not cls_name:
            default = self._default_class()
            if default is not None:
                spec["priorityClassName"] = meta.name(default)
                spec["priority"] = default.get("value", 0)
            else:
                spec.setdefault("priority", 0)
            return
        if cls_name in ("system-cluster-critical", "system-node-critical"):
            spec["priority"] = (2000000000 if cls_name == "system-cluster-critical"
                                else 2000001000)
            return
        try:
            cls = self.store.get("priorityclasses", "", cls_name)
        except kv.NotFoundError:
            raise AdmissionDenied(
                self.name, "no PriorityClass with name %s was found" % cls_name)
        spec["priority"] = cls.get("value", 0)

    def _default_class(self):
        items, _ = self.store.list("priorityclasses")
        for pc in items:
            if pc.get("globalDefault"):
                return pc
        return None


class LimitRanger(AdmissionPlugin):
    """plugin/pkg/admission/limitranger: apply LimitRange default/
    defaultRequest to containers missing requests/limits."""

    name = "LimitRanger"

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        items, _ = self.store.list("limitranges", attrs.namespace or "default")
        defaults_req: dict = {}
        defaults_lim: dict = {}
        for lr in items:
            for lim in (lr.get("spec") or {}).get("limits", []):
                if lim.get("type") != "Container":
                    continue
                defaults_req.update(lim.get("defaultRequest") or {})
                defaults_lim.update(lim.get("default") or {})
        if not defaults_req and not defaults_lim:
            return
        for c in (attrs.obj.get("spec") or {}).get("containers", []):
            res = c.setdefault("resources", {})
            req = res.setdefault("requests", {})
            lim = res.setdefault("limits", {})
            for k, v in defaults_req.items():
                req.setdefault(k, v)
            for k, v in defaults_lim.items():
                lim.setdefault(k, v)
                req.setdefault(k, v)


class ResourceQuota(AdmissionPlugin):
    """plugin/pkg/admission/resourcequota: reject pod creates that would
    push aggregate requests over any ResourceQuota hard limit in the
    namespace.  Usage is recomputed from live pods (the reference keeps a
    quota controller + admission cache; recompute is the same contract)."""

    name = "ResourceQuota"

    # reservations younger than this count toward usage even before the
    # store write lands (closes the check-then-create race between two
    # concurrent admissions; the write happens after validate() returns)
    RESERVATION_TTL = 2.0

    def __init__(self, store: kv.MemoryStore):
        self.store = store
        self._lock = threading.Lock()
        # (ns, pod_name) -> (cpu_milli, mem_bytes, reserved_at)
        self._pending: dict = {}

    def validate(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        ns = attrs.namespace or "default"
        quotas, _ = self.store.list("resourcequotas", ns)
        if not quotas:
            return
        with self._lock:
            pods, _ = self.store.list("pods", ns)
            stored_names = {(p.get("metadata") or {}).get("name")
                            for p in pods}
            now = _time.monotonic()
            self._pending = {
                k: v for k, v in self._pending.items()
                if now - v[2] < self.RESERVATION_TTL
                and k[1] not in stored_names}
            pend = [v for k, v in self._pending.items() if k[0] == ns]
            used_cpu = (sum(self._pod_cpu(p) for p in pods)
                        + sum(v[0] for v in pend))
            used_mem = (sum(self._pod_mem(p) for p in pods)
                        + sum(v[1] for v in pend))
            n_pods = len(pods) + len(pend)
            new_cpu = self._pod_cpu(attrs.obj)
            new_mem = self._pod_mem(attrs.obj)
            for q in quotas:
                hard = (q.get("spec") or {}).get("hard") or {}
                checks = (
                    ("pods", n_pods + 1,
                     lambda v: float(v)),
                    ("requests.cpu", used_cpu + new_cpu,
                     quantity.parse_cpu_milli),
                    ("requests.memory", used_mem + new_mem,
                     quantity.parse_mem_bytes),
                    ("cpu", used_cpu + new_cpu, quantity.parse_cpu_milli),
                    ("memory", used_mem + new_mem, quantity.parse_mem_bytes),
                )
                for key, would_use, parse in checks:
                    if key in hard and would_use > parse(hard[key]):
                        raise AdmissionDenied(
                            self.name,
                            "exceeded quota: %s, requested %s over hard limit"
                            " %s=%s" % (meta.name(q), key, key, hard[key]))
            name = (attrs.obj.get("metadata") or {}).get("name") or attrs.name
            self._pending[(ns, name)] = (new_cpu, new_mem, now)

    @staticmethod
    def _pod_cpu(pod) -> int:
        total = 0
        for c in (pod.get("spec") or {}).get("containers", []):
            req = ((c.get("resources") or {}).get("requests") or {})
            total += quantity.parse_cpu_milli(req.get("cpu", "0"))
        return total

    @staticmethod
    def _pod_mem(pod) -> int:
        total = 0
        for c in (pod.get("spec") or {}).get("containers", []):
            req = ((c.get("resources") or {}).get("requests") or {})
            total += quantity.parse_mem_bytes(req.get("memory", "0"))
        return total


class DefaultTolerationSeconds(AdmissionPlugin):
    """plugin/pkg/admission/defaulttolerationseconds: every pod gets
    not-ready/unreachable NoExecute tolerations for 300s unless it already
    tolerates them."""

    name = "DefaultTolerationSeconds"
    KEYS = ("node.kubernetes.io/not-ready", "node.kubernetes.io/unreachable")

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        spec = attrs.obj.setdefault("spec", {})
        tolerations = spec.setdefault("tolerations", [])
        for key in self.KEYS:
            if any(t.get("key") == key and t.get("effect") == "NoExecute"
                   for t in tolerations):
                continue
            tolerations.append({"key": key, "operator": "Exists",
                                "effect": "NoExecute",
                                "tolerationSeconds": 300})


# -- webhook admission -----------------------------------------------------

class Webhook:
    """One registered webhook (Mutating or Validating).

    match: fn(attrs) -> bool; url receives an AdmissionReview POST.
    failure_policy: 'Ignore' (errors pass) or 'Fail' (errors reject) —
    the same knob as admissionregistration FailurePolicyType.
    """

    def __init__(self, name: str, url: str, mutating: bool = False,
                 failure_policy: str = "Fail", timeout: float = 10.0,
                 match: Optional[Callable[[Attributes], bool]] = None):
        self.name = name
        self.url = url
        self.mutating = mutating
        self.failure_policy = failure_policy
        self.timeout = timeout
        self.match = match or (lambda attrs: True)


class WebhookAdmission(AdmissionPlugin):
    name = "Webhook"

    def __init__(self) -> None:
        self.webhooks: List[Webhook] = []

    def register(self, wh: Webhook) -> None:
        self.webhooks.append(wh)

    def _call(self, wh: Webhook, attrs: Attributes) -> Optional[dict]:
        review = {"kind": "AdmissionReview", "apiVersion": "admission/v1",
                  "request": {"uid": "0", "operation": attrs.verb,
                              "resource": attrs.resource,
                              "subResource": attrs.subresource,
                              "namespace": attrs.namespace,
                              "name": attrs.name,
                              "object": attrs.obj,
                              "oldObject": attrs.old_obj}}
        req = urllib.request.Request(
            wh.url, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            from .egress import CLUSTER, default_selector
            with default_selector.open(CLUSTER, req, wh.timeout) as resp:
                return json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 — network errors hit policy
            if wh.failure_policy == "Ignore":
                logger.warning("webhook %s failed (ignored): %s", wh.name, e)
                return None
            raise AdmissionDenied(wh.name, "webhook call failed: %s" % e)

    def _apply(self, wh: Webhook, attrs: Attributes, phase: str) -> None:
        resp = self._call(wh, attrs)
        if resp is None:
            return
        result = resp.get("response") or {}
        if not result.get("allowed", False):
            msg = ((result.get("status") or {}).get("message")
                   or "admission webhook %s denied the request" % wh.name)
            raise AdmissionDenied(wh.name, msg)
        if phase == "mutate" and result.get("patchType") == "JSONPatch":
            import base64
            ops = json.loads(base64.b64decode(result["patch"]))
            patched = patchlib.json_patch(attrs.obj, ops)
            attrs.obj.clear()
            attrs.obj.update(patched)

    def admit(self, attrs: Attributes) -> None:
        for wh in self.webhooks:
            if wh.mutating and wh.match(attrs):
                self._apply(wh, attrs, "mutate")

    def validate(self, attrs: Attributes) -> None:
        for wh in self.webhooks:
            if not wh.mutating and wh.match(attrs):
                self._apply(wh, attrs, "validate")


def default_chain(store: kv.MemoryStore) -> Chain:
    """The default plugin order (pkg/kubeapiserver/options/plugins.go:64,
    reduced to the reproduced set)."""
    return Chain([
        NamespaceLifecycle(store),
        LimitRanger(store),
        DefaultTolerationSeconds(),
        Priority(store),
        # webhook admission sits between mutating in-tree and quota
        ResourceQuota(store),  # always last (plugins.go keeps quota last)
    ])
