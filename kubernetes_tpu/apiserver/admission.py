"""Admission control chain.

Reference: staging/src/k8s.io/apiserver/pkg/admission (two-phase chain:
all mutating plugins run before all validating plugins; order fixed by
pkg/kubeapiserver/options/plugins.go:64) + in-tree plugins under
plugin/pkg/admission/ + webhook admission
(staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook — AdmissionReview
POSTed to external HTTP endpoints, mutating webhooks may return a JSONPatch).

In-tree set reproduced (the ones meaningful without kubelet-side state):
  NamespaceLifecycle    reject writes into missing/terminating namespaces
  Priority              resolve priorityClassName -> spec.priority
  LimitRanger           apply LimitRange defaults to container resources
  ResourceQuota         reject creates that would exceed a ResourceQuota
  DefaultTolerationSeconds  add default NoExecute tolerations to pods
  TaintNodesByCondition vestigial here (node controller owns taints)
"""

from __future__ import annotations

import json
import logging
import threading
import time as _time
import urllib.request
from typing import Callable, List, Optional

from ..api import meta, quantity
from ..store import kv
from . import patch as patchlib

logger = logging.getLogger(__name__)

CREATE, UPDATE, DELETE, CONNECT = "CREATE", "UPDATE", "DELETE", "CONNECT"


class AdmissionDenied(Exception):
    """Rejection: surfaces as HTTP 400/403 with the plugin name."""

    def __init__(self, plugin: str, message: str):
        super().__init__(message)
        self.plugin = plugin


class Attributes:
    """admission.Attributes (pkg/admission/interfaces.go).  user/groups
    carry the authenticated identity (GetUserInfo) — NodeRestriction and
    OwnerReferencesPermissionEnforcement decide on it."""

    __slots__ = ("verb", "resource", "subresource", "namespace", "name",
                 "obj", "old_obj", "user", "groups")

    def __init__(self, verb: str, resource: str, obj, old_obj=None,
                 namespace: str = "", name: str = "", subresource: str = "",
                 user: str = "", groups: tuple = ()):
        self.verb = verb
        self.resource = resource
        self.subresource = subresource
        self.namespace = namespace
        self.name = name
        self.obj = obj
        self.old_obj = old_obj
        self.user = user
        self.groups = groups


class AdmissionPlugin:
    name = "plugin"

    def admit(self, attrs: Attributes) -> None:
        """Mutating phase: may modify attrs.obj in place or raise."""

    def validate(self, attrs: Attributes) -> None:
        """Validating phase: raise AdmissionDenied to reject."""


class Chain:
    """Runs every plugin's admit(), then every plugin's validate()."""

    def __init__(self, plugins: Optional[List[AdmissionPlugin]] = None):
        self.plugins: List[AdmissionPlugin] = list(plugins or ())

    def register(self, plugin: AdmissionPlugin) -> None:
        self.plugins.append(plugin)

    def run(self, attrs: Attributes) -> None:
        for p in self.plugins:
            p.admit(attrs)
        for p in self.plugins:
            p.validate(attrs)


# -- in-tree plugins -------------------------------------------------------

class NamespaceLifecycle(AdmissionPlugin):
    """plugin/pkg/admission/namespace/lifecycle: creates into a
    nonexistent or terminating namespace are rejected; the immortal
    namespaces (default, kube-system) can't be deleted."""

    name = "NamespaceLifecycle"
    IMMORTAL = {"default", "kube-system", "kube-public"}

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource == "namespaces" and attrs.verb == DELETE:
            if attrs.name in self.IMMORTAL:
                raise AdmissionDenied(self.name,
                                      "this namespace may not be deleted")
            return
        if attrs.verb != CREATE or not attrs.namespace:
            return
        if attrs.resource in ("namespaces", "events"):
            return
        try:
            ns = self.store.get("namespaces", "", attrs.namespace)
        except kv.NotFoundError:
            if attrs.namespace == "default":
                return  # default namespace is implicit
            raise AdmissionDenied(
                self.name, "namespace %r not found" % attrs.namespace)
        phase = ((ns.get("status") or {}).get("phase")
                 or ("Terminating" if meta.deletion_timestamp(ns) else "Active"))
        if phase == "Terminating":
            raise AdmissionDenied(
                self.name,
                "unable to create new content in namespace %s because it is "
                "being terminated" % attrs.namespace)


class Priority(AdmissionPlugin):
    """plugin/pkg/admission/priority: resolve pod.spec.priorityClassName to
    spec.priority; unknown class -> reject; default class applies."""

    name = "Priority"

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        pod = attrs.obj
        spec = pod.setdefault("spec", {})
        cls_name = spec.get("priorityClassName")
        if not cls_name:
            default = self._default_class()
            if default is not None:
                spec["priorityClassName"] = meta.name(default)
                spec["priority"] = default.get("value", 0)
            else:
                spec.setdefault("priority", 0)
            return
        if cls_name in ("system-cluster-critical", "system-node-critical"):
            spec["priority"] = (2000000000 if cls_name == "system-cluster-critical"
                                else 2000001000)
            return
        try:
            cls = self.store.get("priorityclasses", "", cls_name)
        except kv.NotFoundError:
            raise AdmissionDenied(
                self.name, "no PriorityClass with name %s was found" % cls_name)
        spec["priority"] = cls.get("value", 0)

    def _default_class(self):
        items, _ = self.store.list("priorityclasses")
        for pc in items:
            if pc.get("globalDefault"):
                return pc
        return None


class LimitRanger(AdmissionPlugin):
    """plugin/pkg/admission/limitranger: apply LimitRange default/
    defaultRequest to containers missing requests/limits."""

    name = "LimitRanger"

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        items, _ = self.store.list("limitranges", attrs.namespace or "default")
        defaults_req: dict = {}
        defaults_lim: dict = {}
        for lr in items:
            for lim in (lr.get("spec") or {}).get("limits", []):
                if lim.get("type") != "Container":
                    continue
                defaults_req.update(lim.get("defaultRequest") or {})
                defaults_lim.update(lim.get("default") or {})
        if not defaults_req and not defaults_lim:
            return
        for c in (attrs.obj.get("spec") or {}).get("containers", []):
            res = c.setdefault("resources", {})
            req = res.setdefault("requests", {})
            lim = res.setdefault("limits", {})
            for k, v in defaults_req.items():
                req.setdefault(k, v)
            for k, v in defaults_lim.items():
                lim.setdefault(k, v)
                req.setdefault(k, v)


class ResourceQuota(AdmissionPlugin):
    """plugin/pkg/admission/resourcequota: reject pod creates that would
    push aggregate requests over any ResourceQuota hard limit in the
    namespace.  Usage is recomputed from live pods (the reference keeps a
    quota controller + admission cache; recompute is the same contract)."""

    name = "ResourceQuota"

    # reservations younger than this count toward usage even before the
    # store write lands (closes the check-then-create race between two
    # concurrent admissions; the write happens after validate() returns)
    RESERVATION_TTL = 2.0

    def __init__(self, store: kv.MemoryStore):
        self.store = store
        self._lock = threading.Lock()
        # (ns, pod_name) -> (cpu_milli, mem_bytes, reserved_at)
        self._pending: dict = {}

    def validate(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        ns = attrs.namespace or "default"
        quotas, _ = self.store.list("resourcequotas", ns)
        if not quotas:
            return
        with self._lock:
            pods, _ = self.store.list("pods", ns)
            stored_names = {(p.get("metadata") or {}).get("name")
                            for p in pods}
            now = _time.monotonic()
            self._pending = {
                k: v for k, v in self._pending.items()
                if now - v[2] < self.RESERVATION_TTL
                and k[1] not in stored_names}
            pend = [v for k, v in self._pending.items() if k[0] == ns]
            used_cpu = (sum(self._pod_cpu(p) for p in pods)
                        + sum(v[0] for v in pend))
            used_mem = (sum(self._pod_mem(p) for p in pods)
                        + sum(v[1] for v in pend))
            n_pods = len(pods) + len(pend)
            new_cpu = self._pod_cpu(attrs.obj)
            new_mem = self._pod_mem(attrs.obj)
            for q in quotas:
                hard = (q.get("spec") or {}).get("hard") or {}
                checks = (
                    ("pods", n_pods + 1,
                     lambda v: float(v)),
                    ("requests.cpu", used_cpu + new_cpu,
                     quantity.parse_cpu_milli),
                    ("requests.memory", used_mem + new_mem,
                     quantity.parse_mem_bytes),
                    ("cpu", used_cpu + new_cpu, quantity.parse_cpu_milli),
                    ("memory", used_mem + new_mem, quantity.parse_mem_bytes),
                )
                for key, would_use, parse in checks:
                    if key in hard and would_use > parse(hard[key]):
                        raise AdmissionDenied(
                            self.name,
                            "exceeded quota: %s, requested %s over hard limit"
                            " %s=%s" % (meta.name(q), key, key, hard[key]))
            name = (attrs.obj.get("metadata") or {}).get("name") or attrs.name
            self._pending[(ns, name)] = (new_cpu, new_mem, now)

    @staticmethod
    def _pod_cpu(pod) -> int:
        total = 0
        for c in (pod.get("spec") or {}).get("containers", []):
            req = ((c.get("resources") or {}).get("requests") or {})
            total += quantity.parse_cpu_milli(req.get("cpu", "0"))
        return total

    @staticmethod
    def _pod_mem(pod) -> int:
        total = 0
        for c in (pod.get("spec") or {}).get("containers", []):
            req = ((c.get("resources") or {}).get("requests") or {})
            total += quantity.parse_mem_bytes(req.get("memory", "0"))
        return total


class DefaultTolerationSeconds(AdmissionPlugin):
    """plugin/pkg/admission/defaulttolerationseconds: every pod gets
    not-ready/unreachable NoExecute tolerations for 300s unless it already
    tolerates them."""

    name = "DefaultTolerationSeconds"
    KEYS = ("node.kubernetes.io/not-ready", "node.kubernetes.io/unreachable")

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        spec = attrs.obj.setdefault("spec", {})
        tolerations = spec.setdefault("tolerations", [])
        for key in self.KEYS:
            if any(t.get("key") == key and t.get("effect") == "NoExecute"
                   for t in tolerations):
                continue
            tolerations.append({"key": key, "operator": "Exists",
                                "effect": "NoExecute",
                                "tolerationSeconds": 300})


# -- webhook admission -----------------------------------------------------

class NodeRestriction(AdmissionPlugin):
    """plugin/pkg/admission/noderestriction/admission.go:199 — a kubelet
    (user system:node:<name> in group system:nodes) may only write
    objects tied to its OWN node:

      pods           create only pods bound to itself (mirror-pod shape);
                     update/delete only pods already bound to itself
      pods/status    only its own pods' status
      nodes, nodes/status   only its own Node object

    The Node AUTHORIZER already scopes kubelet READS (rbac.py:197);
    this is the write half it cited as missing."""

    name = "NodeRestriction"
    NODE_USER_PREFIX = "system:node:"
    NODES_GROUP = "system:nodes"

    def _node_of(self, attrs: Attributes) -> str | None:
        if (attrs.user.startswith(self.NODE_USER_PREFIX)
                and self.NODES_GROUP in attrs.groups):
            return attrs.user[len(self.NODE_USER_PREFIX):]
        return None

    def admit(self, attrs: Attributes) -> None:
        node_name = self._node_of(attrs)
        if node_name is None:
            return
        if attrs.resource == "pods":
            bound = lambda o: ((o or {}).get("spec") or {}).get("nodeName")  # noqa: E731
            if attrs.verb == CREATE:
                if bound(attrs.obj) != node_name:
                    raise AdmissionDenied(
                        self.name,
                        f"node {node_name!r} can only create pods bound "
                        "to itself")
            elif attrs.verb in (UPDATE, DELETE):
                current = attrs.old_obj or attrs.obj
                if bound(current) != node_name:
                    raise AdmissionDenied(
                        self.name,
                        f"node {node_name!r} cannot modify pod "
                        f"{attrs.namespace}/{attrs.name} bound to "
                        f"{bound(current)!r}")
                if attrs.verb == UPDATE and attrs.obj is not None \
                        and bound(attrs.obj) not in (None, "", node_name):
                    # the NEW object may not move the binding either — a
                    # node credential re-binding its pod elsewhere is the
                    # exact escalation this plugin exists to stop
                    raise AdmissionDenied(
                        self.name,
                        f"node {node_name!r} cannot re-bind pod "
                        f"{attrs.namespace}/{attrs.name} to "
                        f"{bound(attrs.obj)!r}")
        elif attrs.resource == "nodes":
            target = attrs.name or meta.name(attrs.obj or {})
            if target and target != node_name:
                raise AdmissionDenied(
                    self.name,
                    f"node {node_name!r} cannot modify node {target!r}")


class ServiceAccount(AdmissionPlugin):
    """plugin/pkg/admission/serviceaccount: default
    spec.serviceAccountName, require the account to exist, and inject
    the API-access token volume + per-container mounts (the projected
    kube-api-access-* volume every reference pod gets)."""

    name = "ServiceAccount"
    DEFAULT_SA = "default"
    TOKEN_VOLUME = "kube-api-access"
    MOUNT_PATH = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE \
                or attrs.subresource:
            return
        pod = attrs.obj
        spec = pod.setdefault("spec", {})
        sa_name = (spec.get("serviceAccountName")
                   or spec.get("serviceAccount")  # legacy field alias
                   or self.DEFAULT_SA)
        spec["serviceAccountName"] = sa_name
        # read WITHOUT popping: the opt-out is the user's stored intent
        # (stripping it would revert to injection on any recreate)
        if not spec.get("automountServiceAccountToken", True):
            return
        vols = spec.setdefault("volumes", [])
        if any(v.get("name", "").startswith(self.TOKEN_VOLUME)
               for v in vols):
            return  # already injected (e.g. client-provided)
        vol_name = f"{self.TOKEN_VOLUME}-{meta.new_uid()[-6:]}"
        vols.append({
            "name": vol_name,
            "projected": {"sources": [
                {"serviceAccountToken": {"path": "token",
                                         "expirationSeconds": 3607}},
                {"configMap": {"name": "kube-root-ca.crt",
                               "items": [{"key": "ca.crt",
                                          "path": "ca.crt"}]}},
                {"downwardAPI": {"items": [
                    {"path": "namespace",
                     "fieldRef": {"fieldPath": "metadata.namespace"}}]}},
            ]}})
        for c in list(spec.get("containers") or ()) + list(
                spec.get("initContainers") or ()):
            mounts = c.setdefault("volumeMounts", [])
            if not any(m.get("mountPath") == self.MOUNT_PATH
                       for m in mounts):
                mounts.append({"name": vol_name,
                               "mountPath": self.MOUNT_PATH,
                               "readOnly": True})

    def validate(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE \
                or attrs.subresource:
            return
        sa = (attrs.obj.get("spec") or {}).get("serviceAccountName",
                                               self.DEFAULT_SA)
        if sa == self.DEFAULT_SA:
            # the serviceaccount controller creates "default" per
            # namespace asynchronously; like the implicit default
            # NAMESPACE (NamespaceLifecycle above), the default account
            # is treated as implicit so an apiserver running without the
            # controller fleet (perf harness, standalone tests) admits
            # ordinary pods — the reference's harness always runs the SA
            # controller, so its reject-on-missing is the same outcome
            return
        try:
            self.store.get("serviceaccounts", attrs.namespace, sa)
        except kv.NotFoundError:
            # a NAMED account must exist, like the reference
            raise AdmissionDenied(
                self.name,
                f"service account {attrs.namespace}/{sa} not found")


class DefaultStorageClass(AdmissionPlugin):
    """plugin/pkg/admission/storage/storageclass/setdefault: a PVC
    created without spec.storageClassName gets the cluster default
    (StorageClass annotated is-default-class)."""

    name = "DefaultStorageClass"
    DEFAULT_ANN = "storageclass.kubernetes.io/is-default-class"

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "persistentvolumeclaims" \
                or attrs.verb != CREATE:
            return
        spec = attrs.obj.setdefault("spec", {})
        if "storageClassName" in spec:
            return  # explicit class (or explicit "" = no dynamic provisioning)
        classes, _rv = self.store.list("storageclasses", None)
        defaults = [
            c for c in classes
            if (c["metadata"].get("annotations") or {}).get(
                self.DEFAULT_ANN) == "true"]
        if not defaults:
            return  # no default class: leave unset (static binding only)
        # newest default wins (the reference picks by creation time when
        # several are marked default)
        defaults.sort(key=lambda c: c["metadata"].get(
            "creationTimestamp", 0))
        spec["storageClassName"] = meta.name(defaults[-1])


class StorageObjectInUseProtection(AdmissionPlugin):
    """plugin/pkg/admission/storage/storageobjectinuseprotection: add
    the protection finalizers at create; the PV/PVC-protection
    controllers (controllers/volume.py) remove them once the object is
    no longer in use — this is the admission half of that pair."""

    name = "StorageObjectInUseProtection"
    PVC_FINALIZER = "kubernetes.io/pvc-protection"
    PV_FINALIZER = "kubernetes.io/pv-protection"

    def admit(self, attrs: Attributes) -> None:
        if attrs.verb != CREATE:
            return
        fin = (self.PVC_FINALIZER
               if attrs.resource == "persistentvolumeclaims"
               else self.PV_FINALIZER
               if attrs.resource == "persistentvolumes" else None)
        if fin is None:
            return
        fins = attrs.obj.setdefault("metadata", {}).setdefault(
            "finalizers", [])
        if fin not in fins:
            fins.append(fin)


class TaintNodesByCondition(AdmissionPlugin):
    """plugin/pkg/admission/nodetaint: every NEW node starts with the
    not-ready NoSchedule taint so nothing schedules onto it before the
    node lifecycle controller observes a Ready condition and lifts it."""

    name = "TaintNodesByCondition"
    NOT_READY = "node.kubernetes.io/not-ready"

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "nodes" or attrs.verb != CREATE:
            return
        spec = attrs.obj.setdefault("spec", {})
        taints = spec.setdefault("taints", [])
        if not any(t.get("key") == self.NOT_READY for t in taints):
            taints.append({"key": self.NOT_READY, "effect": "NoSchedule"})


class PodSecurity(AdmissionPlugin):
    """pkg/kubeapiserver/options/plugins.go PodSecurity: enforce the Pod
    Security Standards level from the namespace's
    pod-security.kubernetes.io/enforce label.  Reproduced checks:

      baseline    no hostNetwork/hostPID/hostIPC, no privileged
                  containers, no hostPath volumes, no hostPorts
      restricted  baseline + runAsNonRoot, allowPrivilegeEscalation
                  false, capabilities drop ALL

    (k8s.io/pod-security-admission policy checks, reduced to the
    fields this tree models.)"""

    name = "PodSecurity"
    ENFORCE_LABEL = "pod-security.kubernetes.io/enforce"

    def __init__(self, store: kv.MemoryStore):
        self.store = store

    def _level(self, namespace: str) -> str:
        try:
            ns = self.store.get("namespaces", "", namespace)
        except kv.NotFoundError:
            return "privileged"
        return (ns["metadata"].get("labels") or {}).get(
            self.ENFORCE_LABEL, "privileged")

    def validate(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.verb != CREATE:
            return
        level = self._level(attrs.namespace)
        if level == "privileged":
            return
        spec = attrs.obj.get("spec") or {}
        violations: list[str] = []
        for f in ("hostNetwork", "hostPID", "hostIPC"):
            if spec.get(f):
                violations.append(f)
        for v in spec.get("volumes") or ():
            if v.get("hostPath"):
                violations.append(f"hostPath volume {v.get('name')!r}")
        containers = list(spec.get("containers") or ()) + list(
            spec.get("initContainers") or ())
        for c in containers:
            sc = c.get("securityContext") or {}
            if sc.get("privileged"):
                violations.append(f"privileged container {c.get('name')!r}")
            for p in c.get("ports") or ():
                if p.get("hostPort"):
                    violations.append(
                        f"hostPort {p['hostPort']} in {c.get('name')!r}")
            if level == "restricted":
                pod_sc = spec.get("securityContext") or {}
                if not (sc.get("runAsNonRoot")
                        or pod_sc.get("runAsNonRoot")):
                    violations.append(
                        f"runAsNonRoot unset in {c.get('name')!r}")
                if sc.get("allowPrivilegeEscalation", True):
                    violations.append(
                        f"allowPrivilegeEscalation not false in "
                        f"{c.get('name')!r}")
                caps = (sc.get("capabilities") or {})
                if "ALL" not in (caps.get("drop") or ()):
                    violations.append(
                        f"capabilities.drop ALL missing in "
                        f"{c.get('name')!r}")
        if violations:
            raise AdmissionDenied(
                self.name,
                f"violates PodSecurity {level!r}: " + "; ".join(
                    sorted(set(violations))))


class OwnerReferencesPermissionEnforcement(AdmissionPlugin):
    """plugin/pkg/admission/gc: setting blockOwnerDeletion on an owner
    reference requires permission to update the OWNER's finalizers
    (otherwise any pod author could block any object's deletion).
    The authorizer callback is the apiserver's composite authorizer."""

    name = "OwnerReferencesPermissionEnforcement"

    # kind -> resource for the owners this tree models
    KIND_TO_RESOURCE = {
        "ReplicaSet": "replicasets", "Deployment": "deployments",
        "StatefulSet": "statefulsets", "DaemonSet": "daemonsets",
        "Job": "jobs", "CronJob": "cronjobs", "Pod": "pods",
        "ReplicationController": "replicationcontrollers",
        "Node": "nodes", "Service": "services",
    }

    def __init__(self, authorize: Callable | None = None):
        # authorize(user, groups, verb, resource, subresource, ns, name)
        # -> bool; None = enforcement disabled (no authorizer configured)
        self.authorize = authorize

    def validate(self, attrs: Attributes) -> None:
        if self.authorize is None or attrs.verb not in (CREATE, UPDATE):
            return
        refs = ((attrs.obj or {}).get("metadata") or {}).get(
            "ownerReferences") or ()
        old_refs = {(r.get("uid"), bool(r.get("blockOwnerDeletion")))
                    for r in (((attrs.old_obj or {}).get("metadata") or {})
                              .get("ownerReferences") or ())}
        for ref in refs:
            if not ref.get("blockOwnerDeletion"):
                continue
            if (ref.get("uid"), True) in old_refs:
                continue  # unchanged: was already allowed
            res = self.KIND_TO_RESOURCE.get(ref.get("kind", ""))
            if res is None:
                continue
            if not self.authorize(attrs.user, attrs.groups, "update", res,
                                  "finalizers", attrs.namespace,
                                  ref.get("name", "")):
                raise AdmissionDenied(
                    self.name,
                    f"cannot set blockOwnerDeletion on {ref.get('kind')} "
                    f"{ref.get('name')!r}: no permission to update its "
                    "finalizers")


class Webhook:
    """One registered webhook (Mutating or Validating).

    match: fn(attrs) -> bool; url receives an AdmissionReview POST.
    failure_policy: 'Ignore' (errors pass) or 'Fail' (errors reject) —
    the same knob as admissionregistration FailurePolicyType.
    """

    def __init__(self, name: str, url: str, mutating: bool = False,
                 failure_policy: str = "Fail", timeout: float = 10.0,
                 match: Optional[Callable[[Attributes], bool]] = None):
        self.name = name
        self.url = url
        self.mutating = mutating
        self.failure_policy = failure_policy
        self.timeout = timeout
        self.match = match or (lambda attrs: True)


class WebhookAdmission(AdmissionPlugin):
    name = "Webhook"

    def __init__(self) -> None:
        self.webhooks: List[Webhook] = []

    def register(self, wh: Webhook) -> None:
        self.webhooks.append(wh)

    def _call(self, wh: Webhook, attrs: Attributes) -> Optional[dict]:
        review = {"kind": "AdmissionReview", "apiVersion": "admission/v1",
                  "request": {"uid": "0", "operation": attrs.verb,
                              "resource": attrs.resource,
                              "subResource": attrs.subresource,
                              "namespace": attrs.namespace,
                              "name": attrs.name,
                              "object": attrs.obj,
                              "oldObject": attrs.old_obj}}
        req = urllib.request.Request(
            wh.url, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            from .egress import CLUSTER, default_selector
            with default_selector.open(CLUSTER, req, wh.timeout) as resp:
                return json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 — network errors hit policy
            if wh.failure_policy == "Ignore":
                logger.warning("webhook %s failed (ignored): %s", wh.name, e)
                return None
            raise AdmissionDenied(wh.name, "webhook call failed: %s" % e)

    def _apply(self, wh: Webhook, attrs: Attributes, phase: str) -> None:
        resp = self._call(wh, attrs)
        if resp is None:
            return
        result = resp.get("response") or {}
        if not result.get("allowed", False):
            msg = ((result.get("status") or {}).get("message")
                   or "admission webhook %s denied the request" % wh.name)
            raise AdmissionDenied(wh.name, msg)
        if phase == "mutate" and result.get("patchType") == "JSONPatch":
            import base64
            ops = json.loads(base64.b64decode(result["patch"]))
            patched = patchlib.json_patch(attrs.obj, ops)
            attrs.obj.clear()
            attrs.obj.update(patched)

    def admit(self, attrs: Attributes) -> None:
        for wh in self.webhooks:
            if wh.mutating and wh.match(attrs):
                self._apply(wh, attrs, "mutate")

    def validate(self, attrs: Attributes) -> None:
        for wh in self.webhooks:
            if not wh.mutating and wh.match(attrs):
                self._apply(wh, attrs, "validate")


def default_chain(store: kv.MemoryStore,
                  authorize: Callable | None = None,
                  disable: frozenset | set = frozenset()) -> Chain:
    """The default plugin order (pkg/kubeapiserver/options/plugins.go:64,
    reduced to the reproduced set; quota stays last like the reference).
    `authorize` is the apiserver's composite-authorizer callback for
    OwnerReferencesPermissionEnforcement.  `disable` removes plugins by
    name (--disable-admission-plugins; the reference's perf harness
    disables ServiceAccount, TaintNodesByCondition and Priority because
    it runs no controllers — scheduler_perf/util.go:84-85)."""
    chain = Chain([
        NamespaceLifecycle(store),
        NodeRestriction(),
        TaintNodesByCondition(),
        LimitRanger(store),
        ServiceAccount(store),
        DefaultStorageClass(store),
        StorageObjectInUseProtection(),
        DefaultTolerationSeconds(),
        Priority(store),
        PodSecurity(store),
        OwnerReferencesPermissionEnforcement(authorize),
        # webhook admission sits between mutating in-tree and quota
        ResourceQuota(store),  # always last (plugins.go keeps quota last)
    ])
    if disable:
        disable = {d.strip() for d in disable if d and d.strip()}
        known = {p.name for p in chain.plugins}
        unknown = disable - known
        if unknown:
            # fail fast like the reference apiserver: a misspelled name
            # silently leaving a plugin enabled (e.g. the node taint with
            # no controller to lift it) is a debugging pit
            raise ValueError(
                f"unknown admission plugin(s) {sorted(unknown)}; "
                f"known: {sorted(known)}")
        chain.plugins = [p for p in chain.plugins
                         if p.name not in disable]
    return chain
