"""API aggregation: APIService routing/proxying.

Reference: staging/src/k8s.io/kube-aggregator — APIService objects map an
API group/version to a backing service; the aggregation layer sits in
front of kube-apiserver and proxies /apis/<group>/<version>/** to the
registered backend (proxy handler in pkg/apiserver/handler_proxy.go),
serving local groups itself.  Availability is tracked per APIService
(status condition Available), recorded from proxy outcomes on transitions.

An APIService object here:
  metadata.name: "<version>.<group>"  (e.g. "v1beta1.metrics.example.com")
  spec.service.url: backend base URL (our stand-in for service+port
      resolution — the reference resolves a Service reference through the
      cluster network; we are single-host)
  spec.group / spec.version: parsed from name when absent
"""

from __future__ import annotations

import logging
import threading
import urllib.error
import urllib.request

from ..api import meta
from ..store import kv

logger = logging.getLogger(__name__)

APISERVICES = "apiservices"

HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding", "te",
               "upgrade", "proxy-authorization", "proxy-authenticate",
               "content-length", "host"}


class AggregatorRegistry:
    """Maps (group, version) -> backend URL, fed by APIService objects."""

    def __init__(self, store: kv.MemoryStore,
                 local_groups: frozenset[str] | set[str] = frozenset(),
                 is_local=None):
        self.store = store
        # groups the apiserver serves itself.  The reference pre-registers
        # Local APIService objects for built-in groups (kube-aggregator
        # pkg/apiserver/apiservice.go) and its autoregister controller does
        # the same for established CRD groups, so a service-backed
        # APIService can never shadow either.  We enforce the same
        # precedence: a static builtin set plus a dynamic predicate
        # (CRD groups establish AFTER an APIService may have been applied,
        # so the authoritative check happens at resolve time).
        self._local_groups = frozenset(local_groups)
        self._is_local_extra = is_local or (lambda group: False)
        self._lock = threading.Lock()
        # (group, version) -> (backend url, APIService name)
        self._routes: dict[tuple[str, str], tuple[str, str]] = {}
        self._available: dict[str, bool] = {}  # APIService name -> last state
        items, rev = store.list(APISERVICES)
        for obj in items:
            self._apply(obj)
        self._stop = threading.Event()
        # watch resumes from the LIST revision: an APIService created
        # between the list and watch registration must not be lost
        self._watch_thread = threading.Thread(
            target=self._watch_loop, args=(rev,), name="aggregator-watch",
            daemon=True)
        self._watch_thread.start()

    def stop(self) -> None:
        self._stop.set()

    def known_group_versions(self) -> dict[str, set[str]]:
        """group -> versions served by registered (service-backed)
        APIServices — merged into /apis discovery alongside builtins and
        CRDs so advertised groups are reachable at their real versions."""
        out: dict[str, set[str]] = {}
        with self._lock:
            for group, version in self._routes:
                out.setdefault(group, set()).add(version)
        return out

    def _parse(self, obj: dict) -> tuple[str, str] | None:
        spec = obj.get("spec") or {}
        group, version = spec.get("group"), spec.get("version")
        if not (group and version):
            nm = meta.name(obj)
            version, _, group = nm.partition(".")
        if not version:
            return None
        return (group or "", version)

    def _apply(self, obj: dict, deleted: bool = False) -> None:
        gv = self._parse(obj)
        if gv is None:
            return
        if self._group_is_local(gv[0]):
            # locally-served group: ignore the route so an APIService
            # cannot hijack e.g. apps/v1 or an established CRD's traffic
            if not deleted:
                logger.warning(
                    "aggregator: ignoring APIService %s for locally-served "
                    "group %r", meta.name(obj), gv[0])
            return
        url = ((obj.get("spec") or {}).get("service") or {}).get("url")
        with self._lock:
            if deleted or not url:
                self._routes.pop(gv, None)
            else:
                self._routes[gv] = (url.rstrip("/"), meta.name(obj))

    def _watch_loop(self, since_rv: int) -> None:
        w = self.store.watch(APISERVICES, since_rv=since_rv)
        while not self._stop.is_set():
            ev = w.next(timeout=0.5)
            if ev is None:
                continue
            self._apply(ev.object, deleted=(ev.type == kv.DELETED))
        w.stop()

    def backend_for(self, group: str, version: str) -> str | None:
        with self._lock:
            route = self._routes.get((group, version))
            return route[0] if route else None

    def set_availability(self, obj_name: str, available: bool,
                         message: str = "") -> None:
        """Record the Available condition (apiservice status controller)."""
        def patch(o):
            conds = o.setdefault("status", {}).setdefault("conditions", [])
            conds[:] = [c for c in conds if c.get("type") != "Available"]
            conds.append({"type": "Available",
                          "status": "True" if available else "False",
                          "message": message})
            return o
        try:
            self.store.guaranteed_update(APISERVICES, "", obj_name, patch)
        except kv.StoreError:
            pass

    # -- the proxy -------------------------------------------------------

    def _group_is_local(self, group: str) -> bool:
        return (group == "" or group in self._local_groups
                or self._is_local_extra(group))

    def resolve(self, path: str) -> tuple[str, str] | None:
        """(backend url, APIService name) for a proxied path, else None.
        The single route lookup — callers pass the result to proxy_open.
        Locally-served groups (builtins + established CRDs) never resolve
        to a backend, even if a route slipped in before the CRD
        established."""
        parts = [p for p in path.split("/") if p]
        if len(parts) < 3 or parts[0] != "apis":
            return None
        if self._group_is_local(parts[1]):
            return None
        with self._lock:
            return self._routes.get((parts[1], parts[2]))

    def proxy_open(self, backend: str, svc_name: str, method: str, path: str,
                   query: str, body: bytes | None, headers: dict):
        """Open the backend request; returns (status, headers, resp) where
        resp is a file-like to STREAM from (so watch streams relay instead
        of buffering).  Availability transitions are recorded on the
        APIService's Available condition: only CONNECTION failures mark it
        unavailable — an idle-stream timeout mid-relay just ends the
        stream (the client re-watches, reflector semantics)."""
        import io
        url = backend + path + (f"?{query}" if query else "")
        fwd = {k: v for k, v in headers.items()
               if k.lower() not in HOP_HEADERS}
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=fwd)
        try:
            # Cluster-network egress (egress.py): aggregated backends may
            # live behind a konnectivity-style tunnel
            from .egress import CLUSTER, default_selector
            resp = default_selector.open(CLUSTER, req, 30)
            self._observe_availability(svc_name, True)
            return resp.status, dict(resp.headers), resp
        except urllib.error.HTTPError as e:
            # backend responded: it IS available, just unhappy
            self._observe_availability(svc_name, True)
            return e.code, dict(e.headers or {}), e
        except (urllib.error.URLError, OSError) as e:
            logger.warning("aggregator: backend %s unreachable: %s", url, e)
            self._observe_availability(svc_name, False, str(e))
            return (503, {"Content-Type": "application/json"},
                    io.BytesIO(b'{"kind":"Status","status":"Failure",'
                               b'"reason":"ServiceUnavailable",'
                               b'"message":"aggregated apiserver '
                               b'unreachable"}'))

    def proxy(self, method: str, path: str, query: str, body: bytes | None,
              headers: dict) -> tuple[int, dict, bytes] | None:
        """One-shot convenience (tests): resolve + open + read fully."""
        route = self.resolve(path)
        if route is None:
            return None
        status, hdrs, resp = self.proxy_open(route[0], route[1], method,
                                             path, query, body, headers)
        with resp:
            return status, hdrs, resp.read()

    def _observe_availability(self, svc_name: str, available: bool,
                              message: str = "") -> None:
        """Write the Available condition only on transitions (keeps the
        per-request path write-free in steady state)."""
        with self._lock:
            if self._available.get(svc_name) == available:
                return
            self._available[svc_name] = available
        self.set_availability(svc_name, available, message)
