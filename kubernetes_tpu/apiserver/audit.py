"""Audit logging.

Reference: staging/src/k8s.io/apiserver/pkg/audit + plugin/pkg/audit/log —
a policy maps requests to audit levels (None/Metadata/Request/
RequestResponse); events are emitted at stage RequestReceived and
ResponseComplete as JSON lines to a log backend.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional, TextIO

LEVEL_NONE = "None"
LEVEL_METADATA = "Metadata"
LEVEL_REQUEST = "Request"
LEVEL_REQUEST_RESPONSE = "RequestResponse"

_LEVELS = [LEVEL_NONE, LEVEL_METADATA, LEVEL_REQUEST, LEVEL_REQUEST_RESPONSE]


class PolicyRule:
    def __init__(self, level: str, resources: Optional[List[str]] = None,
                 verbs: Optional[List[str]] = None,
                 users: Optional[List[str]] = None):
        self.level = level
        self.resources = resources
        self.verbs = verbs
        self.users = users

    def matches(self, user: str, verb: str, resource: str) -> bool:
        return ((self.resources is None or resource in self.resources)
                and (self.verbs is None or verb in self.verbs)
                and (self.users is None or user in self.users))


class Policy:
    """First matching rule wins (audit policy semantics)."""

    def __init__(self, rules: Optional[List[PolicyRule]] = None,
                 default_level: str = LEVEL_METADATA):
        self.rules = list(rules or ())
        self.default_level = default_level

    def level_for(self, user: str, verb: str, resource: str) -> str:
        for rule in self.rules:
            if rule.matches(user, verb, resource):
                return rule.level
        return self.default_level


class AuditLogger:
    def __init__(self, policy: Optional[Policy] = None,
                 sink: Optional[Callable[[dict], None]] = None,
                 stream: Optional[TextIO] = None,
                 max_events: int = 10000):
        self.policy = policy or Policy()
        self.sink = sink
        self.stream = stream
        self.max_events = max_events
        self._lock = threading.Lock()
        self.events: List[dict] = []  # in-memory ring (tests, /debug)
        self._counter = 0

    def log(self, stage: str, user: str, verb: str, resource: str,
            namespace: str = "", name: str = "", code: int = 0,
            obj: Optional[dict] = None) -> Optional[dict]:
        level = self.policy.level_for(user, verb, resource)
        if level == LEVEL_NONE:
            return None
        with self._lock:
            self._counter += 1
            audit_id = "audit-%d" % self._counter
        event = {
            "kind": "Event", "apiVersion": "audit.k8s.io/v1",
            "auditID": audit_id, "stage": stage, "level": level,
            "verb": verb.lower(),
            "user": {"username": user},
            "objectRef": {"resource": resource, "namespace": namespace,
                          "name": name},
            "requestReceivedTimestamp": time.time(),
        }
        if code:
            event["responseStatus"] = {"code": code}
        if obj is not None and level in (LEVEL_REQUEST,
                                         LEVEL_REQUEST_RESPONSE):
            event["requestObject"] = obj
        with self._lock:
            self.events.append(event)
            if len(self.events) > self.max_events:
                del self.events[: len(self.events) - self.max_events]
            if self.stream is not None:  # serialize writers: no interleaving
                self.stream.write(json.dumps(event) + "\n")
                self.stream.flush()
        if self.sink is not None:
            self.sink(event)
        return event

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self.events)
