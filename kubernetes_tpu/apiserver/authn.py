"""Request authenticators beyond bearer-token lookup.

Reference:
  - X.509 client certs: staging/src/k8s.io/apiserver/pkg/authentication/
    request/x509/x509.go — the CommonName is the user, each Organization
    is a group, trust anchored on --client-ca-file.
  - ServiceAccount tokens: pkg/serviceaccount/jwt.go + the TokenRequest
    subresource (pkg/registry/core/serviceaccount/storage/token.go) —
    signed JWTs carrying system:serviceaccount:{ns}:{name}, validated
    for signature, expiry, and the account still existing.

TPU-stack shape: the apiserver is an in-process HTTP server, so TLS is
an `ssl`-module wrap of its listening socket and the peer certificate
arrives via SSLSocket.getpeercert().  SA tokens are HS256 JWTs over a
cluster-held signing secret persisted in kube-system (restart-stable),
rather than RSA-signed — the validation contract (signature, exp,
account liveness) is the same.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import time

SA_ISSUER = "kubernetes-tpu/serviceaccount"
SA_KEY_SECRET = "serviceaccount-signing-key"
# the apiserver's own token audience: tokens minted for external
# audiences (vault, etc.) must NOT authenticate here (jwt.go audience
# validation against --api-audiences)
API_AUDIENCE = "kubernetes-tpu"


# -- X.509 ---------------------------------------------------------------

def x509_identity(peercert: dict | None
                  ) -> tuple[str, tuple[str, ...]] | None:
    """(user, groups) from an SSLSocket.getpeercert() dict: CN is the
    user, O values are the groups (x509.go CommonNameUserConversion)."""
    if not peercert:
        return None
    user = None
    groups: list[str] = []
    for rdn in peercert.get("subject") or ():
        for key, value in rdn:
            if key == "commonName":
                user = value
            elif key == "organizationName":
                groups.append(value)
    if not user:
        return None
    return user, tuple(groups)


# -- ServiceAccount JWTs -------------------------------------------------

def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _unb64url(text: str) -> bytes:
    pad = "=" * (-len(text) % 4)
    return base64.urlsafe_b64decode(text + pad)


class ServiceAccountIssuer:
    """Mint + verify ServiceAccount JWTs (jwt.go's signer/validator pair).

    The signing key lives in a kube-system Secret so tokens survive an
    apiserver restart the way the reference's --service-account-key-file
    does; first boot generates it."""

    def __init__(self, store):
        from ..api import meta
        from ..store import kv
        self._store = store
        try:
            sec = store.get("secrets", "kube-system", SA_KEY_SECRET)
            self._key = base64.b64decode(sec["data"]["key"])
        except kv.NotFoundError:
            self._key = secrets.token_bytes(32)
            sec = meta.new_object("Secret", SA_KEY_SECRET, "kube-system")
            sec["type"] = "kubernetes-tpu/sa-signing-key"
            sec["data"] = {"key": base64.b64encode(self._key).decode()}
            try:
                store.create("secrets", sec)
            except kv.AlreadyExistsError:  # racing twin: adopt its key
                sec = store.get("secrets", "kube-system", SA_KEY_SECRET)
                self._key = base64.b64decode(sec["data"]["key"])

    def _sign(self, signing_input: bytes) -> str:
        return _b64url(hmac.new(self._key, signing_input,
                                hashlib.sha256).digest())

    def issue(self, namespace: str, name: str, uid: str = "",
              expiration_seconds: int = 3600,
              audiences: tuple[str, ...] = ()) -> tuple[str, float]:
        """-> (token, expiry unix time).  No audience = bound to the
        apiserver's own API_AUDIENCE (TokenRequest defaulting)."""
        now = time.time()
        exp = now + int(expiration_seconds)
        claims = {
            "iss": SA_ISSUER,
            "sub": f"system:serviceaccount:{namespace}:{name}",
            "iat": int(now), "exp": int(exp),
            "aud": list(audiences) or [API_AUDIENCE],
            "kubernetes.io": {"namespace": namespace,
                              "serviceaccount": {"name": name,
                                                 "uid": uid}},
        }
        header = _b64url(json.dumps({"alg": "HS256",
                                     "typ": "JWT"}).encode())
        payload = _b64url(json.dumps(claims).encode())
        signing_input = f"{header}.{payload}".encode()
        return f"{header}.{payload}.{self._sign(signing_input)}", exp

    def verify(self, token: str) -> tuple[str, tuple[str, ...]] | None:
        """(user, groups) or None.  Checks signature, issuer, expiry,
        and that the ServiceAccount object still exists (jwt.go's
        private-claims validation deletes tokens of deleted accounts)."""
        from ..store import kv
        parts = token.split(".")
        if len(parts) != 3:
            return None
        signing_input = f"{parts[0]}.{parts[1]}".encode()
        if not hmac.compare_digest(self._sign(signing_input), parts[2]):
            return None
        try:
            claims = json.loads(_unb64url(parts[1]))
        except (ValueError, json.JSONDecodeError):
            return None
        if claims.get("iss") != SA_ISSUER:
            return None
        aud = claims.get("aud")
        if isinstance(aud, str):
            aud = [aud]
        if not aud or API_AUDIENCE not in aud:
            return None  # token bound to someone else's audience
        try:
            if float(claims.get("exp", 0)) < time.time():
                return None
        except (TypeError, ValueError):
            return None
        sub = claims.get("sub") or ""
        prefix = "system:serviceaccount:"
        if not sub.startswith(prefix):
            return None
        ns, _, name = sub[len(prefix):].partition(":")
        if not ns or not name:
            return None
        try:
            self._store.get("serviceaccounts", ns, name)
        except kv.NotFoundError:
            return None
        return sub, ("system:serviceaccounts",
                     f"system:serviceaccounts:{ns}")


# -- serving/client certificate material ---------------------------------

def issue_cert(ca, common_name: str, organizations: tuple[str, ...] = (),
               dns_sans: tuple[str, ...] = (), ip_sans: tuple[str, ...] = (),
               days: int = 365, server: bool = False) -> tuple[str, str]:
    """(cert_pem, key_pem) signed by the ClusterCA — the certs phase of
    kubeadm (app/phases/certs) for apiserver serving + client certs."""
    import datetime
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    attrs += [x509.NameAttribute(NameOID.ORGANIZATION_NAME, o)
              for o in organizations]
    now = datetime.datetime.now(datetime.timezone.utc)
    eku = (ExtendedKeyUsageOID.SERVER_AUTH if server
           else ExtendedKeyUsageOID.CLIENT_AUTH)
    builder = (x509.CertificateBuilder()
               .subject_name(x509.Name(attrs))
               .issuer_name(ca.cert.subject)
               .public_key(key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now)
               .not_valid_after(now + datetime.timedelta(days=days))
               .add_extension(x509.ExtendedKeyUsage([eku]), critical=False))
    sans: list[x509.GeneralName] = [x509.DNSName(d) for d in dns_sans]
    sans += [x509.IPAddress(ipaddress.ip_address(ip)) for ip in ip_sans]
    if sans:
        builder = builder.add_extension(
            x509.SubjectAlternativeName(sans), critical=False)
    cert = builder.sign(ca.key, hashes.SHA256())
    cert_pem = cert.public_bytes(serialization.Encoding.PEM).decode()
    key_pem = key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()).decode()
    return cert_pem, key_pem


def write_serving_bundle(ca, cert_dir: str,
                         host: str = "127.0.0.1") -> dict[str, str]:
    """Materialize apiserver TLS serving files under cert_dir; returns
    {"cert_file", "key_file", "client_ca_file"} for APIServer(tls=...)."""
    import os
    cert_pem, key_pem = issue_cert(
        ca, "kube-apiserver",
        dns_sans=("localhost", "kubernetes", "kubernetes.default"),
        ip_sans=(host,) if host else ("127.0.0.1",), server=True)
    os.makedirs(cert_dir, exist_ok=True)
    paths = {"cert_file": os.path.join(cert_dir, "apiserver.crt"),
             "key_file": os.path.join(cert_dir, "apiserver.key"),
             "client_ca_file": os.path.join(cert_dir, "ca.crt")}
    with open(paths["cert_file"], "w") as f:
        f.write(cert_pem)
    with open(paths["key_file"], "w") as f:
        os.fchmod(f.fileno(), 0o600)
        f.write(key_pem)
    with open(paths["client_ca_file"], "w") as f:
        f.write(ca.ca_pem())
    return paths
