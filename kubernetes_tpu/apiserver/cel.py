"""CEL-subset evaluator for CRD validation rules.

Reference: staging/src/k8s.io/apiextensions-apiserver/pkg/apiserver/
schema/cel/ — x-kubernetes-validations carries CEL expressions over
`self` (and `oldSelf` on update) that must hold for a write to be
admitted.

The reference links google/cel-go; nothing equivalent is available
here, so this is an independent interpreter for the subset of CEL that
CRD rules in the wild overwhelmingly use:

  literals        int/float/string ('x' or "x")/bool/null, lists [a,b]
  identifiers     self, oldSelf, bound loop vars
  selection       a.b.c (absent field -> error, like CEL)
  indexing        a[i], map[key]
  operators       == != < <= > >= + - * / % ! && || ? : in
  macros          has(a.b), size(x), all/exists/exists_one(x, v, expr)
  functions       x.startsWith(s) .endsWith(s) .contains(s) .matches(re)
                  string(x) int(x) double(x)

Evaluation is total and sandboxed: no attribute access on Python
objects (only dict/list traversal), no callables beyond the table
above, recursion and iteration bounded by the object's size.  Parse or
eval failure raises CELError — the apiserver maps it to a 422 exactly
like a failing rule, which is CEL's own posture (errors are failures,
not passes).
"""

from __future__ import annotations

import re

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<float>\d+\.\d+)
    | (?P<int>\d+)
    | (?P<string>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<op>&&|\|\||[=!<>]=|[-+*/%().,\[\]<>!?:])
    )""", re.VERBOSE)

_KEYWORDS = {"true": True, "false": False, "null": None}


class CELError(Exception):
    pass


def _tokenize(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None or m.end() == pos:
            rest = src[pos:].strip()
            if not rest:
                break
            raise CELError(f"bad token at {rest[:20]!r}")
        pos = m.end()
        for kind in ("float", "int", "string", "ident", "op"):
            val = m.group(kind)
            if val is not None:
                out.append((kind, val))
                break
    out.append(("end", ""))
    return out


class _Parser:
    """Precedence-climbing parser producing a nested-tuple AST."""

    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        tok = self.toks[self.i]
        self.i += 1
        return tok

    def expect(self, op: str):
        kind, val = self.next()
        if kind != "op" or val != op:
            raise CELError(f"expected {op!r}, got {val!r}")

    def parse(self):
        node = self.ternary()
        if self.peek()[0] != "end":
            raise CELError(f"trailing input at {self.peek()[1]!r}")
        return node

    def ternary(self):
        cond = self.or_()
        if self.peek() == ("op", "?"):
            self.next()
            then = self.ternary()
            self.expect(":")
            other = self.ternary()
            return ("?:", cond, then, other)
        return cond

    def or_(self):
        node = self.and_()
        while self.peek() == ("op", "||"):
            self.next()
            node = ("||", node, self.and_())
        return node

    def and_(self):
        node = self.cmp()
        while self.peek() == ("op", "&&"):
            self.next()
            node = ("&&", node, self.cmp())
        return node

    def cmp(self):
        node = self.add()
        kind, val = self.peek()
        if (kind, val) in (("op", "=="), ("op", "!="), ("op", "<"),
                           ("op", "<="), ("op", ">"), ("op", ">=")) \
                or (kind, val) == ("ident", "in"):
            self.next()
            return (val, node, self.add())
        return node

    def add(self):
        node = self.mul()
        while self.peek() in (("op", "+"), ("op", "-")):
            op = self.next()[1]
            node = (op, node, self.mul())
        return node

    def mul(self):
        node = self.unary()
        while self.peek() in (("op", "*"), ("op", "/"), ("op", "%")):
            op = self.next()[1]
            node = (op, node, self.unary())
        return node

    def unary(self):
        if self.peek() == ("op", "!"):
            self.next()
            return ("!", self.unary())
        if self.peek() == ("op", "-"):
            self.next()
            return ("neg", self.unary())
        return self.postfix()

    def postfix(self):
        node = self.primary()
        while True:
            kind, val = self.peek()
            if (kind, val) == ("op", "."):
                self.next()
                name_kind, name = self.next()
                if name_kind != "ident":
                    raise CELError(f"expected field name, got {name!r}")
                if self.peek() == ("op", "("):
                    node = ("call", name, node, self._args())
                else:
                    node = ("sel", node, name)
            elif (kind, val) == ("op", "["):
                self.next()
                idx = self.ternary()
                self.expect("]")
                node = ("idx", node, idx)
            else:
                return node

    def _args(self):
        self.expect("(")
        args = []
        if self.peek() != ("op", ")"):
            args.append(self.ternary())
            while self.peek() == ("op", ","):
                self.next()
                args.append(self.ternary())
        self.expect(")")
        return args

    def primary(self):
        kind, val = self.next()
        if kind == "int":
            return ("lit", int(val))
        if kind == "float":
            return ("lit", float(val))
        if kind == "string":
            body = val[1:-1]
            return ("lit", re.sub(r"\\(.)", r"\1", body))
        if kind == "ident":
            if val in _KEYWORDS:
                return ("lit", _KEYWORDS[val])
            if self.peek() == ("op", "("):
                return ("fn", val, self._args())
            return ("var", val)
        if (kind, val) == ("op", "("):
            node = self.ternary()
            self.expect(")")
            return node
        if (kind, val) == ("op", "["):
            items = []
            if self.peek() != ("op", "]"):
                items.append(self.ternary())
                while self.peek() == ("op", ","):
                    self.next()
                    items.append(self.ternary())
            self.expect("]")
            return ("list", items)
        raise CELError(f"unexpected {val!r}")


_MACROS = {"all", "exists", "exists_one", "map", "filter"}


def _eval(node, env: dict):
    op = node[0]
    if op == "lit":
        return node[1]
    if op == "var":
        if node[1] not in env:
            raise CELError(f"unknown identifier {node[1]!r}")
        return env[node[1]]
    if op == "list":
        return [_eval(n, env) for n in node[1]]
    if op == "sel":
        base = _eval(node[1], env)
        if isinstance(base, dict):
            if node[2] not in base:
                raise CELError(f"no such field {node[2]!r}")
            return base[node[2]]
        raise CELError(f"cannot select {node[2]!r} from {type(base).__name__}")
    if op == "idx":
        base = _eval(node[1], env)
        idx = _eval(node[2], env)
        try:
            if isinstance(base, list) and isinstance(idx, int):
                return base[idx]
            if isinstance(base, dict):
                return base[idx]
        except (KeyError, IndexError):
            raise CELError(f"index {idx!r} out of range") from None
        raise CELError("bad indexing")
    if op == "!":
        return not _truthy(_eval(node[1], env))
    if op == "neg":
        val = _eval(node[1], env)
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            raise CELError("negation of non-number")
        return -val
    if op == "&&":
        return _truthy(_eval(node[1], env)) and _truthy(_eval(node[2], env))
    if op == "||":
        return _truthy(_eval(node[1], env)) or _truthy(_eval(node[2], env))
    if op == "?:":
        return (_eval(node[2], env) if _truthy(_eval(node[1], env))
                else _eval(node[3], env))
    if op in ("==", "!=", "<", "<=", ">", ">=", "+", "-", "*", "/", "%",
              "in"):
        left, right = _eval(node[1], env), _eval(node[2], env)
        return _binop(op, left, right)
    if op == "fn":
        if node[1] == "has":  # macro: args must stay unevaluated
            return _fn("has", [], node[2], env)
        return _fn(node[1], [_eval(a, env) for a in node[2]], node[2], env)
    if op == "call":
        return _method(node[1], node[2], node[3], env)
    raise CELError(f"bad node {op!r}")


def _truthy(val) -> bool:
    if not isinstance(val, bool):
        raise CELError("non-boolean in boolean context")
    return val


def _binop(op, left, right):
    try:
        if op == "==":
            return left == right
        if op == "!=":
            return left != right
        if op == "in":
            if isinstance(right, (list, str)):
                return left in right
            if isinstance(right, dict):
                return left in right
            raise CELError("'in' needs list/map/string")
        if op == "+" and isinstance(left, str) and isinstance(right, str):
            return left + right
        if op == "+" and isinstance(left, list) and isinstance(right, list):
            return left + right
        if isinstance(left, bool) or isinstance(right, bool):
            raise CELError(f"bad operands for {op}")
        if isinstance(left, (int, float)) and isinstance(right, (int, float)):
            if op == "<":
                return left < right
            if op == "<=":
                return left <= right
            if op == ">":
                return left > right
            if op == ">=":
                return left >= right
            if op == "+":
                return left + right
            if op == "-":
                return left - right
            if op == "*":
                return left * right
            if op == "/":
                if right == 0:
                    raise CELError("division by zero")
                if isinstance(left, int) and isinstance(right, int):
                    # CEL truncates toward zero (C semantics), not
                    # Python's floor: -7/2 is -3, not -4
                    quotient = abs(left) // abs(right)
                    return quotient if (left < 0) == (right < 0) \
                        else -quotient
                return left / right
            if op == "%":
                if right == 0:
                    raise CELError("modulo by zero")
                if isinstance(left, int) and isinstance(right, int):
                    # remainder keeps the dividend's sign: -7%2 is -1
                    remainder = abs(left) % abs(right)
                    return remainder if left >= 0 else -remainder
                return left % right
        if isinstance(left, str) and isinstance(right, str) \
                and op in ("<", "<=", ">", ">="):
            return {"<": left < right, "<=": left <= right,
                    ">": left > right, ">=": left >= right}[op]
    except TypeError:
        pass
    raise CELError(f"bad operands for {op}: "
                   f"{type(left).__name__}, {type(right).__name__}")


def _fn(name, args, raw_args, env):
    if name == "size" and len(args) == 1:
        if isinstance(args[0], (str, list, dict)):
            return len(args[0])
        raise CELError("size() of non-sized value")
    if name == "has" and len(raw_args) == 1:
        # macro: has(a.b) is true iff selecting b off a succeeds
        node = raw_args[0]
        if node[0] != "sel":
            raise CELError("has() needs a field selection")
        try:
            _eval(node, env)
            return True
        except CELError:
            return False
    if name == "string" and len(args) == 1:
        if isinstance(args[0], bool):
            return "true" if args[0] else "false"
        return str(args[0])
    if name == "int" and len(args) == 1:
        try:
            return int(args[0])
        except (TypeError, ValueError):
            raise CELError("int() conversion failed") from None
    if name == "double" and len(args) == 1:
        try:
            return float(args[0])
        except (TypeError, ValueError):
            raise CELError("double() conversion failed") from None
    raise CELError(f"unknown function {name}()")


def _method(name, recv_node, arg_nodes, env):
    if name in _MACROS:
        # comprehension macros: recv.all(v, expr) etc.
        recv = _eval(recv_node, env)
        if not isinstance(recv, (list, dict)):
            raise CELError(f"{name}() needs a list/map")
        items = list(recv)  # maps iterate their KEYS, like CEL
        if len(arg_nodes) != 2 or arg_nodes[0][0] != "var":
            raise CELError(f"{name}(var, expr) expected")
        var = arg_nodes[0][1]
        body = arg_nodes[1]
        results = []
        for item in items:
            results.append(_eval(body, {**env, var: item}))
        if name == "all":
            return all(_truthy(r) for r in results)
        if name == "exists":
            return any(_truthy(r) for r in results)
        if name == "exists_one":
            return sum(1 for r in results if _truthy(r)) == 1
        if name == "filter":
            return [i for i, r in zip(items, results) if _truthy(r)]
        if name == "map":
            return results
    recv = _eval(recv_node, env)
    args = [_eval(a, env) for a in arg_nodes]
    if isinstance(recv, str) and len(args) == 1 \
            and isinstance(args[0], str):
        if name == "startsWith":
            return recv.startswith(args[0])
        if name == "endsWith":
            return recv.endswith(args[0])
        if name == "contains":
            return args[0] in recv
        if name == "matches":
            try:
                return re.search(args[0], recv) is not None
            except re.error as e:
                raise CELError(f"bad regex: {e}") from None
    raise CELError(f"unknown method {name}()")


# process-local: compiled-expression memo keyed by rule text; each
# apiserver/child process rebuilds its own copy on first evaluate()
_CACHE: dict[str, tuple] = {}


def evaluate(rule: str, self_obj, old_self=None) -> bool:
    """True iff `rule` holds for self (and oldSelf when given)."""
    ast = _CACHE.get(rule)
    if ast is None:
        ast = _Parser(_tokenize(rule)).parse()
        if len(_CACHE) > 1024:
            _CACHE.clear()
        _CACHE[rule] = ast
    env = {"self": self_obj}
    if old_self is not None:
        env["oldSelf"] = old_self
    result = _eval(ast, env)
    if not isinstance(result, bool):
        raise CELError("rule did not evaluate to a boolean")
    return result
