"""CustomResourceDefinition serving.

Reference: staging/src/k8s.io/apiextensions-apiserver — CRD objects create
new REST resources at /apis/{group}/{version}/...; custom objects are
validated against the CRD's openAPIV3Schema (structural-schema subset:
type, required, properties, items, enum, minimum/maximum, pattern) and
stored like any built-in.  The coscheduling PodGroup CRD rides this.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional

CRDS = "customresourcedefinitions"


class ValidationError(ValueError):
    pass


def validate_schema(obj, schema: dict, path: str = "") -> None:
    """Validate obj against an openAPIV3Schema subset."""
    if not schema:
        return
    typ = schema.get("type")
    where = path or "<root>"
    if typ == "object" or (typ is None and "properties" in schema):
        if not isinstance(obj, dict):
            raise ValidationError("%s: expected object" % where)
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in obj:
                raise ValidationError("%s: missing required field %r"
                                      % (where, req))
        for key, val in obj.items():
            if key in props:
                validate_schema(val, props[key], path + "." + key)
            elif schema.get("additionalProperties") is False:
                raise ValidationError("%s: unknown field %r" % (where, key))
    elif typ == "array":
        if not isinstance(obj, list):
            raise ValidationError("%s: expected array" % where)
        items = schema.get("items")
        if items:
            for i, v in enumerate(obj):
                validate_schema(v, items, "%s[%d]" % (path, i))
    elif typ == "string":
        if not isinstance(obj, str):
            raise ValidationError("%s: expected string" % where)
        pat = schema.get("pattern")
        if pat and not re.search(pat, obj):
            raise ValidationError("%s: does not match pattern %s"
                                  % (where, pat))
    elif typ == "integer":
        if not isinstance(obj, int) or isinstance(obj, bool):
            raise ValidationError("%s: expected integer" % where)
    elif typ == "number":
        if not isinstance(obj, (int, float)) or isinstance(obj, bool):
            raise ValidationError("%s: expected number" % where)
    elif typ == "boolean":
        if not isinstance(obj, bool):
            raise ValidationError("%s: expected boolean" % where)
    if "enum" in schema and obj not in schema["enum"]:
        raise ValidationError("%s: %r not in enum %s"
                              % (where, obj, schema["enum"]))
    if isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if "minimum" in schema and obj < schema["minimum"]:
            raise ValidationError("%s: %s below minimum %s"
                                  % (where, obj, schema["minimum"]))
        if "maximum" in schema and obj > schema["maximum"]:
            raise ValidationError("%s: %s above maximum %s"
                                  % (where, obj, schema["maximum"]))


class CRDRegistry:
    """Tracks established CRDs; maps (group, plural) -> serving info."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_plural: Dict[str, dict] = {}

    def establish(self, crd_obj: dict, dry_run: bool = False) -> dict:
        """Validate + index a CRD object; returns it with status set.

        dry_run validates and stamps status WITHOUT indexing — callers
        run that before the store write (422 on bad spec) and commit
        the index only after the write succeeds, so a CAS-rejected
        update can't change what the server serves."""
        spec = crd_obj.get("spec") or {}
        group = spec.get("group")
        names = spec.get("names") or {}
        plural = names.get("plural")
        kind = names.get("kind")
        if not group or not plural or not kind:
            raise ValidationError(
                "CRD needs spec.group, spec.names.plural, spec.names.kind")
        versions = spec.get("versions") or [{"name": "v1", "served": True,
                                             "storage": True}]
        served = [v for v in versions if v.get("served", True)]
        if not served:
            raise ValidationError("CRD has no served versions")
        info = {
            "group": group, "plural": plural, "kind": kind,
            "singular": names.get("singular", kind.lower()),
            "short_names": names.get("shortNames", []),
            "namespaced": spec.get("scope", "Namespaced") == "Namespaced",
            "versions": [v["name"] for v in served],
            "schemas": {v["name"]: ((v.get("schema") or {})
                                    .get("openAPIV3Schema") or {})
                        for v in served},
        }
        if not dry_run:
            with self._lock:
                self._by_plural[plural] = info
                for short in info["short_names"]:
                    self._by_plural.setdefault(short, info)
        crd_obj.setdefault("status", {})["conditions"] = [
            {"type": "Established", "status": "True"}]
        return crd_obj

    def remove(self, crd_obj: dict) -> None:
        names = (crd_obj.get("spec") or {}).get("names") or {}
        with self._lock:
            info = self._by_plural.pop(names.get("plural", ""), None)
            if info:
                for short in info["short_names"]:
                    if self._by_plural.get(short) is info:
                        del self._by_plural[short]

    def lookup(self, plural: str) -> Optional[dict]:
        with self._lock:
            return self._by_plural.get(plural)

    def groups(self) -> set:
        """API groups currently served by established CRDs.  The aggregator
        treats these as locally-served (the reference's autoregister
        controller pins Local APIServices for CRD groups)."""
        with self._lock:
            return {info["group"] for info in self._by_plural.values()}

    def resources(self) -> List[dict]:
        with self._lock:
            seen = []
            for info in self._by_plural.values():
                if info not in seen:
                    seen.append(info)
            return seen

    def validate_object(self, plural: str, version: str, obj: dict) -> None:
        info = self.lookup(plural)
        if info is None:
            raise ValidationError("no CRD for resource %r" % plural)
        if version not in info["versions"]:
            raise ValidationError("version %r not served for %r"
                                  % (version, plural))
        schema = info["schemas"].get(version) or {}
        validate_schema(obj, schema)
