"""CustomResourceDefinition serving.

Reference: staging/src/k8s.io/apiextensions-apiserver — CRD objects create
new REST resources at /apis/{group}/{version}/...; custom objects are
validated against the CRD's openAPIV3Schema (structural-schema subset:
type, required, properties, items, enum, minimum/maximum, pattern) and
stored like any built-in.  The coscheduling PodGroup CRD rides this.

Depth beyond the basic registry (each maps to an apiextensions
subsystem):
  - structural pruning + defaulting (pkg/apiserver/schema/pruning,
    defaulting): unknown fields are dropped on write unless
    x-kubernetes-preserve-unknown-fields; schema `default`s fill absent
    fields
  - CEL validation rules (pkg/apiserver/schema/cel):
    x-kubernetes-validations [{rule, message}] evaluated against `self`
    (+ `oldSelf` on update) at every schema level, via cel.py
  - multi-version + conversion (pkg/apiserver/conversion): objects are
    STORED at the single storage version and converted on the wire;
    strategy None rewrites apiVersion, strategy Webhook POSTs a
    ConversionReview to the configured URL
  - status/scale subresources (pkg/registry/customresource): served
    only when spec.subresources declares them; scale reads/writes
    through the configured JSON paths
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional

CRDS = "customresourcedefinitions"


class ValidationError(ValueError):
    pass


# -- structural schema: pruning + defaulting -----------------------------

def prune(obj, schema: dict, root: bool = True):
    """Drop fields not in the structural schema (pruning.Prune):
    unknown fields vanish on write instead of persisting as junk.
    x-kubernetes-preserve-unknown-fields or a non-False
    additionalProperties keeps a subtree as-is."""
    if not schema or schema.get("x-kubernetes-preserve-unknown-fields"):
        return obj
    if isinstance(obj, dict):
        props = schema.get("properties")
        addl = schema.get("additionalProperties")
        if props is None and not isinstance(addl, dict):
            return obj  # untyped object: nothing to prune against
        out = {}
        for key, val in obj.items():
            if root and key in ("apiVersion", "kind", "metadata"):
                out[key] = val  # ObjectMeta is never pruned
            elif props is not None and key in props:
                out[key] = prune(val, props[key], root=False)
            elif isinstance(addl, dict):
                # map values prune against the value schema
                out[key] = prune(val, addl, root=False)
            elif addl not in (None, False):
                out[key] = val
        return out
    if isinstance(obj, list) and schema.get("items"):
        return [prune(v, schema["items"], root=False) for v in obj]
    return obj


def apply_defaults(obj, schema: dict):
    """Fill absent fields carrying a schema `default`
    (defaulting.Default) — applied after pruning, before validation."""
    if not schema:
        return obj
    if isinstance(obj, dict):
        props = schema.get("properties") or {}
        for key, sub in props.items():
            if key not in obj and "default" in sub:
                import copy
                obj[key] = copy.deepcopy(sub["default"])
            if key in obj:
                obj[key] = apply_defaults(obj[key], sub)
        addl = schema.get("additionalProperties")
        if isinstance(addl, dict):
            for key in obj:
                if key not in props:
                    obj[key] = apply_defaults(obj[key], addl)
    elif isinstance(obj, list) and schema.get("items"):
        obj = [apply_defaults(v, schema["items"]) for v in obj]
    return obj


def validate_rules(obj, schema: dict, old=None, path: str = "") -> None:
    """x-kubernetes-validations: CEL rules hold at every schema level,
    with `self` bound to the value at that level (schema/cel/validation
    .go).  A rule error fails the write, same as a false rule."""
    if not schema:
        return
    from . import cel
    where = path or "<root>"
    for entry in schema.get("x-kubernetes-validations") or ():
        rule = entry.get("rule")
        if not rule:
            continue
        if old is None and "oldSelf" in rule:
            # transition rules only run where an old value exists to
            # correlate against (cel/validation.go) — never on create
            continue
        try:
            ok = cel.evaluate(rule, obj, old)
        except cel.CELError as e:
            raise ValidationError(
                f"{where}: rule {rule!r} errored: {e}") from None
        if not ok:
            raise ValidationError(
                f"{where}: {entry.get('message') or 'failed rule: ' + rule}")
    if isinstance(obj, dict):
        props = schema.get("properties") or {}
        for key, sub in props.items():
            if key in obj:
                old_val = old.get(key) if isinstance(old, dict) else None
                validate_rules(obj[key], sub, old_val, f"{path}.{key}")
        addl = schema.get("additionalProperties")
        if isinstance(addl, dict):
            for key, val in obj.items():
                if key not in props:
                    old_val = (old.get(key)
                               if isinstance(old, dict) else None)
                    validate_rules(val, addl, old_val, f"{path}.{key}")
    elif isinstance(obj, list) and schema.get("items"):
        for i, val in enumerate(obj):
            validate_rules(val, schema["items"], None, f"{path}[{i}]")


def validate_schema(obj, schema: dict, path: str = "") -> None:
    """Validate obj against an openAPIV3Schema subset."""
    if not schema:
        return
    typ = schema.get("type")
    where = path or "<root>"
    if typ == "object" or (typ is None and "properties" in schema):
        if not isinstance(obj, dict):
            raise ValidationError("%s: expected object" % where)
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in obj:
                raise ValidationError("%s: missing required field %r"
                                      % (where, req))
        for key, val in obj.items():
            if key in props:
                validate_schema(val, props[key], path + "." + key)
            elif schema.get("additionalProperties") is False:
                raise ValidationError("%s: unknown field %r" % (where, key))
    elif typ == "array":
        if not isinstance(obj, list):
            raise ValidationError("%s: expected array" % where)
        items = schema.get("items")
        if items:
            for i, v in enumerate(obj):
                validate_schema(v, items, "%s[%d]" % (path, i))
    elif typ == "string":
        if not isinstance(obj, str):
            raise ValidationError("%s: expected string" % where)
        pat = schema.get("pattern")
        if pat and not re.search(pat, obj):
            raise ValidationError("%s: does not match pattern %s"
                                  % (where, pat))
    elif typ == "integer":
        if not isinstance(obj, int) or isinstance(obj, bool):
            raise ValidationError("%s: expected integer" % where)
    elif typ == "number":
        if not isinstance(obj, (int, float)) or isinstance(obj, bool):
            raise ValidationError("%s: expected number" % where)
    elif typ == "boolean":
        if not isinstance(obj, bool):
            raise ValidationError("%s: expected boolean" % where)
    if "enum" in schema and obj not in schema["enum"]:
        raise ValidationError("%s: %r not in enum %s"
                              % (where, obj, schema["enum"]))
    if isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if "minimum" in schema and obj < schema["minimum"]:
            raise ValidationError("%s: %s below minimum %s"
                                  % (where, obj, schema["minimum"]))
        if "maximum" in schema and obj > schema["maximum"]:
            raise ValidationError("%s: %s above maximum %s"
                                  % (where, obj, schema["maximum"]))


class CRDRegistry:
    """Tracks established CRDs; maps (group, plural) -> serving info."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_plural: Dict[str, dict] = {}

    def establish(self, crd_obj: dict, dry_run: bool = False) -> dict:
        """Validate + index a CRD object; returns it with status set.

        dry_run validates and stamps status WITHOUT indexing — callers
        run that before the store write (422 on bad spec) and commit
        the index only after the write succeeds, so a CAS-rejected
        update can't change what the server serves."""
        spec = crd_obj.get("spec") or {}
        group = spec.get("group")
        names = spec.get("names") or {}
        plural = names.get("plural")
        kind = names.get("kind")
        if not group or not plural or not kind:
            raise ValidationError(
                "CRD needs spec.group, spec.names.plural, spec.names.kind")
        versions = spec.get("versions") or [{"name": "v1", "served": True,
                                             "storage": True}]
        served = [v for v in versions if v.get("served", True)]
        if not served:
            raise ValidationError("CRD has no served versions")
        storage = [v["name"] for v in versions if v.get("storage")]
        if len(storage) > 1:
            raise ValidationError("CRD declares %d storage versions; "
                                  "exactly one allowed" % len(storage))
        if not storage and len(versions) > 1:
            # a single version is unambiguously the storage version;
            # multiple versions with none flagged would make the
            # storage form arbitrary (apiextensions requires exactly
            # one storage=true)
            raise ValidationError(
                "multi-version CRD must flag exactly one storage version")
        conversion = spec.get("conversion") or {"strategy": "None"}
        strategy = conversion.get("strategy", "None")
        if strategy not in ("None", "Webhook"):
            raise ValidationError(f"unknown conversion strategy "
                                  f"{strategy!r}")
        if strategy == "Webhook" and not ((conversion.get("webhook") or {})
                                          .get("clientConfig") or {}
                                          ).get("url"):
            raise ValidationError(
                "Webhook conversion needs webhook.clientConfig.url")
        info = {
            "group": group, "plural": plural, "kind": kind,
            "singular": names.get("singular", kind.lower()),
            "short_names": names.get("shortNames", []),
            "namespaced": spec.get("scope", "Namespaced") == "Namespaced",
            "versions": [v["name"] for v in served],
            "storage_version": storage[0] if storage
            else served[0]["name"],
            "schemas": {v["name"]: ((v.get("schema") or {})
                                    .get("openAPIV3Schema") or {})
                        for v in served},
            "conversion": conversion,
            "subresources": spec.get("subresources") or {},
        }
        if not dry_run:
            with self._lock:
                self._by_plural[plural] = info
                for short in info["short_names"]:
                    self._by_plural.setdefault(short, info)
        crd_obj.setdefault("status", {})["conditions"] = [
            {"type": "Established", "status": "True"}]
        return crd_obj

    def remove(self, crd_obj: dict) -> None:
        names = (crd_obj.get("spec") or {}).get("names") or {}
        with self._lock:
            info = self._by_plural.pop(names.get("plural", ""), None)
            if info:
                for short in info["short_names"]:
                    if self._by_plural.get(short) is info:
                        del self._by_plural[short]

    def lookup(self, plural: str) -> Optional[dict]:
        with self._lock:
            return self._by_plural.get(plural)

    def groups(self) -> set:
        """API groups currently served by established CRDs.  The aggregator
        treats these as locally-served (the reference's autoregister
        controller pins Local APIServices for CRD groups)."""
        with self._lock:
            return {info["group"] for info in self._by_plural.values()}

    def resources(self) -> List[dict]:
        with self._lock:
            seen = []
            for info in self._by_plural.values():
                if info not in seen:
                    seen.append(info)
            return seen

    def validate_object(self, plural: str, version: str, obj: dict) -> None:
        info = self.lookup(plural)
        if info is None:
            raise ValidationError("no CRD for resource %r" % plural)
        if version not in info["versions"]:
            raise ValidationError("version %r not served for %r"
                                  % (version, plural))
        schema = info["schemas"].get(version) or {}
        validate_schema(obj, schema)

    def coerce(self, plural: str, version: str, obj: dict,
               old: dict | None = None) -> dict:
        """The full custom-resource write pipeline: prune unknown
        fields, apply defaults, validate the structural schema, then
        the CEL rules (with oldSelf on update).  Returns the object to
        persist."""
        info = self.lookup(plural)
        if info is None:
            raise ValidationError("no CRD for resource %r" % plural)
        if version not in info["versions"]:
            raise ValidationError("version %r not served for %r"
                                  % (version, plural))
        schema = info["schemas"].get(version) or {}
        obj = prune(obj, schema)
        obj = apply_defaults(obj, schema)
        validate_schema(obj, schema)
        if old is not None:
            # transition rules compare same-shaped objects: the stored
            # old object converts to the REQUEST version first
            old = self.convert(plural, old, version)
        validate_rules(obj, schema, old)
        return obj

    # -- multi-version conversion ----------------------------------------

    def convert(self, plural: str, obj: dict, target_version: str) -> dict:
        """Serve `obj` at target_version (conversion/converter.go).

        None strategy: same schema at every version — only apiVersion
        is rewritten.  Webhook: POST a ConversionReview to the CRD's
        configured URL and take the returned converted object."""
        return self.convert_many(plural, [obj], target_version)[0]

    def convert_many(self, plural: str, objs: list[dict],
                     target_version: str) -> list[dict]:
        """Batch conversion: one ConversionReview for every object that
        needs converting (the protocol's `objects` list), so a list of
        N webhook-strategy objects costs one round trip, not N."""
        info = self.lookup(plural)
        if info is None:
            return objs
        need = [i for i, o in enumerate(objs)
                if (o.get("apiVersion") or "").rpartition("/")[2]
                not in ("", target_version)]
        if not need:
            return objs
        out = list(objs)
        if info["conversion"].get("strategy", "None") == "None":
            for i in need:
                converted = dict(objs[i])
                converted["apiVersion"] = \
                    f"{info['group']}/{target_version}"
                out[i] = converted
            return out
        converted = self._webhook_convert(info, [objs[i] for i in need],
                                          target_version)
        for slot, obj in zip(need, converted):
            out[slot] = obj
        return out

    def _webhook_convert(self, info: dict, objs: list[dict],
                         target_version: str) -> list[dict]:
        import json
        import urllib.request
        import uuid
        url = info["conversion"]["webhook"]["clientConfig"]["url"]
        review = {
            "kind": "ConversionReview",
            "apiVersion": "apiextensions.k8s.io/v1",
            "request": {"uid": uuid.uuid4().hex,
                        "desiredAPIVersion":
                            f"{info['group']}/{target_version}",
                        "objects": objs},
        }
        req = urllib.request.Request(
            url, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read())
        except (OSError, ValueError) as e:
            raise ValidationError(
                f"conversion webhook {url} failed: {e}") from None
        response = body.get("response") or {}
        if (response.get("result") or {}).get("status") == "Failure":
            raise ValidationError(
                "conversion webhook rejected: "
                + str((response.get("result") or {}).get("message")))
        converted = response.get("convertedObjects") or []
        if len(converted) != len(objs):
            raise ValidationError(
                "conversion webhook returned %d objects for %d inputs"
                % (len(converted), len(objs)))
        return converted

    def to_storage(self, plural: str, obj: dict) -> dict:
        info = self.lookup(plural)
        if info is None:
            return obj
        return self.convert(plural, obj, info["storage_version"])

    # -- subresource declarations ----------------------------------------

    def has_status_subresource(self, plural: str) -> bool:
        info = self.lookup(plural)
        return bool(info and "status" in info["subresources"])

    def scale_paths(self, plural: str) -> Optional[dict]:
        info = self.lookup(plural)
        if info is None:
            return None
        return info["subresources"].get("scale")
