"""Group/version/resource discovery + a minimal OpenAPI v2 document.

Reference: staging/src/k8s.io/apiserver/pkg/endpoints/discovery/ —
  GET /api                  APIVersions
  GET /api/v1               APIResourceList (core resources+subresources)
  GET /apis                 APIGroupList (group -> versions/preferred)
  GET /apis/{g}             APIGroup
  GET /apis/{g}/{v}         APIResourceList
  GET /openapi/v2           swagger skeleton (kube-openapi aggregation)

This is what lets a foreign client (kubectl, the aggregator, client
generators) resolve resources from the SERVER instead of a baked-in
table; cli/kubectl.py falls back to these endpoints for resources its
static map doesn't know (CRD-defined kinds included).
"""

from __future__ import annotations

from .. import __version__

# core (legacy "/api/v1") resources: plural -> (Kind, shortNames)
CORE_KINDS: dict[str, tuple[str, tuple[str, ...]]] = {
    "pods": ("Pod", ("po",)),
    "nodes": ("Node", ("no",)),
    "services": ("Service", ("svc",)),
    "endpoints": ("Endpoints", ("ep",)),
    "events": ("Event", ("ev",)),
    "namespaces": ("Namespace", ("ns",)),
    "configmaps": ("ConfigMap", ("cm",)),
    "secrets": ("Secret", ()),
    "serviceaccounts": ("ServiceAccount", ("sa",)),
    "persistentvolumeclaims": ("PersistentVolumeClaim", ("pvc",)),
    "persistentvolumes": ("PersistentVolume", ("pv",)),
    "replicationcontrollers": ("ReplicationController", ("rc",)),
    "podgroups": ("PodGroup", ("pg",)),
    "resourcequotas": ("ResourceQuota", ("quota",)),
    "limitranges": ("LimitRange", ("limits",)),
}

# grouped resources: plural -> Kind, shortNames (group comes from the
# server's BUILTIN_GROUPS routing table so the two can't diverge)
GROUP_KINDS: dict[str, tuple[str, tuple[str, ...]]] = {
    "deployments": ("Deployment", ("deploy",)),
    "replicasets": ("ReplicaSet", ("rs",)),
    "statefulsets": ("StatefulSet", ("sts",)),
    "daemonsets": ("DaemonSet", ("ds",)),
    "jobs": ("Job", ()),
    "cronjobs": ("CronJob", ("cj",)),
    "poddisruptionbudgets": ("PodDisruptionBudget", ("pdb",)),
    "priorityclasses": ("PriorityClass", ("pc",)),
    "storageclasses": ("StorageClass", ("sc",)),
    "csinodes": ("CSINode", ()),
    "volumeattachments": ("VolumeAttachment", ()),
    "leases": ("Lease", ()),
    "customresourcedefinitions": ("CustomResourceDefinition",
                                  ("crd", "crds")),
    "horizontalpodautoscalers": ("HorizontalPodAutoscaler", ("hpa",)),
    "certificatesigningrequests": ("CertificateSigningRequest", ("csr",)),
    "endpointslices": ("EndpointSlice", ()),
    "apiservices": ("APIService", ()),
    "flowschemas": ("FlowSchema", ()),
    "prioritylevelconfigurations": ("PriorityLevelConfiguration", ()),
}

# non-v1 preferred versions (everything else serves v1)
GROUP_PREFERRED_VERSION = {"autoscaling": "v2"}

STANDARD_VERBS = ["create", "delete", "deletecollection", "get", "list",
                  "patch", "update", "watch"]

# subresources surfaced in discovery: (parent plural, subresource, kind,
# verbs) — mirrors the server's SUBRESOURCES/NODE_STREAM routing
_SUBRESOURCES = [
    ("pods", "status", "Pod", ["get", "patch", "update"]),
    ("pods", "binding", "Binding", ["create"]),
    ("pods", "eviction", "Eviction", ["create"]),
    ("pods", "log", "Pod", ["get"]),
    ("pods", "exec", "PodExecOptions", ["create", "get"]),
    ("pods", "attach", "PodAttachOptions", ["create", "get"]),
    ("pods", "portforward", "PodPortForwardOptions", ["create", "get"]),
    ("serviceaccounts", "token", "TokenRequest", ["create"]),
]


def _resource_entry(plural: str, kind: str, namespaced: bool,
                    short_names: tuple[str, ...] = ()) -> dict:
    entry = {"name": plural, "singularName": kind.lower(), "kind": kind,
             "namespaced": namespaced, "verbs": STANDARD_VERBS}
    if short_names:
        entry["shortNames"] = list(short_names)
    return entry


def api_versions() -> dict:
    from ..api import core_versions as corever
    return {"kind": "APIVersions",
            "versions": list(corever.SERVED_VERSIONS)}


def core_versioned_resource_list(version: str,
                                 cluster_scoped: frozenset[str]) -> dict:
    """Resource list for a NON-hub core version: only the resources the
    conversion seam serves there (api/core_versions)."""
    from ..api import core_versions as corever
    resources = []
    served = set()
    for plural, (kind, shorts) in sorted(CORE_KINDS.items()):
        if corever.handles(plural, version):
            served.add(plural)
            resources.append(_resource_entry(
                plural, kind, plural not in cluster_scoped, shorts))
    for parent, sub, kind, verbs in _SUBRESOURCES:
        if parent in served:
            resources.append({"name": f"{parent}/{sub}", "kind": kind,
                              "namespaced": True, "verbs": verbs})
    return {"kind": "APIResourceList", "groupVersion": version,
            "resources": resources}


def core_resource_list(cluster_scoped: frozenset[str],
                       scalable: set[str]) -> dict:
    resources = []
    for plural, (kind, shorts) in sorted(CORE_KINDS.items()):
        resources.append(_resource_entry(
            plural, kind, plural not in cluster_scoped, shorts))
        if plural in scalable:
            resources.append({"name": f"{plural}/scale", "kind": "Scale",
                              "namespaced": True,
                              "verbs": ["get", "patch", "update"]})
    for parent, sub, kind, verbs in _SUBRESOURCES:
        resources.append({"name": f"{parent}/{sub}", "kind": kind,
                          "namespaced": True, "verbs": verbs})
    return {"kind": "APIResourceList", "groupVersion": "v1",
            "resources": resources}


def _version_rank(v: str):
    """kube version-priority ordering: v2 > v1 > v1beta2 > v1beta1 >
    v1alpha1 > anything unparseable (pkg/version kubeVersionPriority)."""
    import re
    m = re.fullmatch(r"v(\d+)(?:(alpha|beta)(\d+)?)?", v)
    if not m:
        return (-1, 0, 0, v)
    major = int(m.group(1))
    stage = {"alpha": 0, "beta": 1, None: 2}[m.group(2)]
    return (0, major, stage, int(m.group(3) or 0))


def _group_versions(group: str, builtin_groups: dict, crd_registry,
                    extra: dict[str, set] | None = None) -> list[str]:
    """Versions the server actually serves for `group` — builtin groups
    contribute their routed version, CRDs their served versions,
    aggregated APIServices their registered versions.  No phantom v1
    for groups that only exist at other versions."""
    versions: set[str] = set()
    if group in builtin_groups:
        versions.add(GROUP_PREFERRED_VERSION.get(group, "v1"))
    for info in crd_registry.resources():
        if info["group"] == group:
            versions.update(info["versions"])
    versions.update((extra or {}).get(group, ()))
    return sorted(versions, key=_version_rank, reverse=True)


def _api_group(group: str, versions: list[str]) -> dict:
    preferred = versions[0]
    return {"name": group,
            "versions": [{"groupVersion": f"{group}/{v}", "version": v}
                         for v in versions],
            "preferredVersion": {"groupVersion": f"{group}/{preferred}",
                                 "version": preferred}}


def group_list(builtin_groups: dict, crd_registry,
               extra: dict[str, set] | None = None) -> dict:
    groups = (set(builtin_groups) | crd_registry.groups()
              | set(extra or ()))
    out = []
    for g in sorted(groups):
        versions = _group_versions(g, builtin_groups, crd_registry, extra)
        if versions:
            out.append(dict(_api_group(g, versions), kind="APIGroup"))
    return {"kind": "APIGroupList", "groups": out}


def api_group(group: str, builtin_groups: dict, crd_registry,
              extra: dict[str, set] | None = None) -> dict | None:
    versions = _group_versions(group, builtin_groups, crd_registry, extra)
    if not versions:
        return None
    return dict(_api_group(group, versions), kind="APIGroup",
                apiVersion="v1")


def group_resource_list(group: str, version: str, builtin_groups: dict,
                        cluster_scoped: frozenset[str], scalable: set[str],
                        crd_registry) -> dict | None:
    resources = []
    if version == GROUP_PREFERRED_VERSION.get(group, "v1"):
        for plural in sorted(builtin_groups.get(group, ())):
            kind, shorts = GROUP_KINDS.get(plural, (plural.title(), ()))
            resources.append(_resource_entry(
                plural, kind, plural not in cluster_scoped, shorts))
            if plural in scalable:
                resources.append({"name": f"{plural}/scale",
                                  "kind": "Scale", "namespaced": True,
                                  "verbs": ["get", "patch", "update"]})
    for info in crd_registry.resources():
        if info["group"] == group and version in info["versions"]:
            resources.append(_resource_entry(
                info["plural"], info["kind"], info["namespaced"],
                tuple(info.get("short_names") or ())))
    if not resources:
        return None
    return {"kind": "APIResourceList",
            "groupVersion": f"{group}/{version}", "resources": resources}


def openapi_v2(builtin_groups: dict, cluster_scoped: frozenset[str],
               crd_registry) -> dict:
    """A skeleton swagger doc: enough structure (paths keyed by route,
    definitions keyed by group/version/kind) for a client to enumerate
    what the server serves — kube-openapi's aggregated spec shape
    without per-field schemas for built-ins; CRDs embed their real
    openAPIV3Schema."""
    paths: dict[str, dict] = {}
    definitions: dict[str, dict] = {}

    def add(gv_prefix: str, gv_key: str, plural: str, kind: str,
            namespaced: bool, schema: dict | None = None):
        base = (f"{gv_prefix}/namespaces/{{namespace}}/{plural}"
                if namespaced else f"{gv_prefix}/{plural}")
        paths[base] = {"get": {}, "post": {}}
        paths[base + "/{name}"] = {"get": {}, "put": {}, "patch": {},
                                   "delete": {}}
        definitions[f"{gv_key}.{kind}"] = schema or {
            "type": "object",
            "x-kubernetes-group-version-kind": [
                {"group": gv_key.rpartition("/")[0] if "/" in gv_key
                 else "", "kind": kind,
                 "version": gv_key.rpartition("/")[2]}]}

    for plural, (kind, _) in CORE_KINDS.items():
        add("/api/v1", "v1", plural, kind, plural not in cluster_scoped)
    for group, plurals in builtin_groups.items():
        version = GROUP_PREFERRED_VERSION.get(group, "v1")
        for plural in plurals:
            kind, _ = GROUP_KINDS.get(plural, (plural.title(), ()))
            add(f"/apis/{group}/{version}", f"{group}/{version}", plural,
                kind, plural not in cluster_scoped)
    for info in crd_registry.resources():
        for version in info["versions"]:
            add(f"/apis/{info['group']}/{version}",
                f"{info['group']}/{version}", info["plural"],
                info["kind"], info["namespaced"],
                schema=info["schemas"].get(version) or None)
    from .openapi_schemas import install
    install(definitions)  # real field trees for the load-bearing kinds
    return {"swagger": "2.0",
            "info": {"title": "kubernetes-tpu", "version": __version__},
            "paths": paths, "definitions": definitions}


# -- OpenAPI v3 (kube-openapi handler3: a discovery index of per-
# group-version documents, lazily fetched by clients) ------------------

def openapi_v3_index(builtin_groups: dict, crd_registry) -> dict:
    """GET /openapi/v3: group-version -> server-relative doc URL."""
    from ..api import core_versions as corever
    gvs = [f"api/{v}" for v in corever.SERVED_VERSIONS]
    for group in builtin_groups:
        version = GROUP_PREFERRED_VERSION.get(group, "v1")
        gvs.append(f"apis/{group}/{version}")
    for info in crd_registry.resources():
        for version in info["versions"]:
            gvs.append(f"apis/{info['group']}/{version}")
    return {"paths": {gv: {"serverRelativeURL": f"/openapi/v3/{gv}"}
                      for gv in sorted(set(gvs))}}


def _v2_schema_to_v3(node):
    """Rewrite swagger-2 $refs into OpenAPI-3 component refs, deep."""
    if isinstance(node, dict):
        out = {}
        for k, v in node.items():
            if k == "$ref" and isinstance(v, str) \
                    and v.startswith("#/definitions/"):
                out[k] = "#/components/schemas/" + v[len("#/definitions/"):]
            else:
                out[k] = _v2_schema_to_v3(v)
        return out
    if isinstance(node, list):
        return [_v2_schema_to_v3(x) for x in node]
    return node


def openapi_v3_group(gv: str, builtin_groups: dict,
                     cluster_scoped: frozenset[str],
                     crd_registry) -> dict | None:
    """GET /openapi/v3/{gv}: an OpenAPI 3.0 document for one
    group-version, built from the same source of truth as the v2 doc
    (paths filtered to the gv; definitions -> components.schemas with
    rewritten refs).  None for anything not in the /openapi/v3 index
    (a real apiserver 404s un-indexed keys — 'apis' or 'apis/apps'
    must not return a merged catch-all document)."""
    index = openapi_v3_index(builtin_groups, crd_registry)["paths"]
    if gv not in index:
        return None
    full = openapi_v2(builtin_groups, cluster_scoped, crd_registry)
    prefix = "/" + gv + "/"
    paths = {p: spec for p, spec in full["paths"].items()
             if p.startswith(prefix)}
    if not paths and gv.startswith("api/"):
        # non-hub core version: the v2 doc only carries hub paths;
        # synthesize this version's routes from the conversion seam's
        # served-resource table so the doc is never empty
        from ..api import core_versions as corever
        version = gv[len("api/"):]
        for plural, (kind, _s) in CORE_KINDS.items():
            if not corever.handles(plural, version):
                continue
            namespaced = plural not in cluster_scoped
            base = (f"/api/{version}/namespaces/{{namespace}}/{plural}"
                    if namespaced else f"/api/{version}/{plural}")
            paths[base] = {"get": {}, "post": {}}
            paths[base + "/{name}"] = {"get": {}, "put": {},
                                       "patch": {}, "delete": {}}
    schemas = _v2_schema_to_v3(full["definitions"])
    return {"openapi": "3.0.0",
            "info": {"title": "kubernetes-tpu", "version": __version__},
            "paths": paths,
            "components": {"schemas": schemas}}
