"""Egress selector — how the apiserver dials OUT (the konnectivity seam).

Reference: staging/src/k8s.io/apiserver/pkg/server/egressselector/
  egress_selector.go:40 — outbound connections are classified by traffic
  type (Cluster: webhooks/aggregated APIs on cluster networks; Master:
  control-plane peers; Etcd: storage) and each type resolves to a dialer.
  The default is a direct dial; deployments with isolated node networks
  plug in the konnectivity client, which tunnels through a proxy server.

Here the seam is a process-global EgressSelector the aggregator,
admission webhooks, and scheduler extender consult for every outbound
request.  Two dialers ship:
  DirectDialer       — plain urllib (the default; zero behavior change)
  HTTPConnectDialer  — tunnels TCP through an HTTP CONNECT proxy (the
                       konnectivity-server protocol's public analog),
                       demonstrating that isolated-network deployments
                       only swap the dialer, never the callers.
"""

from __future__ import annotations

import http.client
import socket
import threading
import urllib.request

CLUSTER = "cluster"
MASTER = "master"
ETCD = "etcd"


class DirectDialer:
    """Default: dial the target directly."""

    def open(self, req: urllib.request.Request, timeout: float):
        return urllib.request.urlopen(req, timeout=timeout)


class HTTPConnectDialer:
    """Tunnel through an HTTP CONNECT proxy (egress_selector.go's
    http-connect protocol).  Only http:// targets — this control plane
    serves plain HTTP."""

    def __init__(self, proxy_host: str, proxy_port: int):
        self.proxy_host = proxy_host
        self.proxy_port = proxy_port

    def open(self, req: urllib.request.Request, timeout: float):
        host = req.host.rsplit(":", 1)[0]
        port = int(req.host.rsplit(":", 1)[1]) if ":" in req.host else 80
        conn = http.client.HTTPConnection(self.proxy_host, self.proxy_port,
                                          timeout=timeout)
        conn.set_tunnel(host, port)
        path = req.selector or "/"
        conn.request(req.get_method(), path, body=req.data,
                     headers=dict(req.header_items()))
        resp = conn.getresponse()
        # adapt to the urlopen-ish contract callers use (read/close/status)
        resp.url = req.full_url
        if resp.status >= 400:
            raise urllib.error.HTTPError(req.full_url, resp.status,
                                         resp.reason, resp.headers, resp)
        return resp


class EgressSelector:
    """network-context -> dialer registry (EgressSelector.Lookup)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dialers: dict[str, object] = {}
        self._default = DirectDialer()

    def register(self, network: str, dialer) -> None:
        with self._lock:
            self._dialers[network] = dialer

    def reset(self, network: str | None = None) -> None:
        with self._lock:
            if network is None:
                self._dialers.clear()
            else:
                self._dialers.pop(network, None)

    def lookup(self, network: str):
        with self._lock:
            return self._dialers.get(network, self._default)

    def open(self, network: str, req: urllib.request.Request,
             timeout: float):
        """Dial `req` through the network's dialer."""
        return self.lookup(network).open(req, timeout)


# the process-global selector every outbound caller consults; tests and
# deployments swap dialers here (server startup wiring in the reference)
default_selector = EgressSelector()
