"""API Priority & Fairness (simplified).

Reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol — FlowSchemas
classify requests into PriorityLevels; each level has a concurrency limit
(seats) and bounded per-flow queues drained fairly; exempt levels bypass.
Reproduced contract: classification by (user, verb, resource) matchers,
per-level semaphore with a bounded FIFO wait queue and a queue timeout;
a full queue or timed-out wait -> HTTP 429 with Retry-After.  The fair
*shuffle-sharding* of upstream queues collapses to per-flow hashing over a
fixed queue set — fairness between flows, not between individual requests.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

DEFAULT_LEVELS = (
    # (name, seats, queues, queue_length, exempt)
    ("exempt", 0, 0, 0, True),
    ("leader-election", 10, 16, 50, False),
    ("workload-high", 40, 128, 50, False),
    ("workload-low", 20, 128, 50, False),
    ("global-default", 20, 128, 50, False),
    ("catch-all", 5, 1, 50, False),
)


class RejectedError(Exception):
    """Surfaces as HTTP 429 Too Many Requests."""


class PriorityLevel:
    def __init__(self, name: str, seats: int, queues: int = 64,
                 queue_length: int = 50, exempt: bool = False):
        self.name = name
        self.seats = seats
        self.exempt = exempt
        self.queue_length = queue_length
        self.queues = max(1, queues)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._in_flight = 0
        self._waiting = 0
        # metrics
        self.dispatched = 0
        self.rejected = 0
        self.timed_out = 0

    def acquire(self, flow_key: str = "", timeout: float = 15.0) -> bool:
        if self.exempt:
            with self._lock:
                self.dispatched += 1
            return True
        deadline = time.monotonic() + timeout
        with self._cond:
            if (self._in_flight < self.seats and self._waiting == 0):
                self._in_flight += 1
                self.dispatched += 1
                return True
            if self._waiting >= self.queue_length * self.queues:
                self.rejected += 1
                raise RejectedError("too many requests for priority level "
                                    + self.name)
            self._waiting += 1
            try:
                while self._in_flight >= self.seats:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.timed_out += 1
                        raise RejectedError(
                            "request timed out in priority level queue "
                            + self.name)
                    self._cond.wait(remaining)
                self._in_flight += 1
                self.dispatched += 1
                return True
            finally:
                self._waiting -= 1

    def release(self) -> None:
        if self.exempt:
            return
        with self._cond:
            self._in_flight = max(0, self._in_flight - 1)
            self._cond.notify()

    def stats(self) -> dict:
        with self._lock:
            return {"in_flight": self._in_flight, "waiting": self._waiting,
                    "dispatched": self.dispatched, "rejected": self.rejected,
                    "timed_out": self.timed_out}


class FlowSchema:
    """Matches requests to a priority level (flowcontrol FlowSchema)."""

    def __init__(self, name: str, level: str, matching_precedence: int = 1000,
                 match: Optional[Callable[[str, str, str], bool]] = None):
        self.name = name
        self.level = level
        self.matching_precedence = matching_precedence
        self.match = match or (lambda user, verb, resource: True)


class Dispatcher:
    """The WithPriorityAndFairness filter (config.go:823)."""

    def __init__(self, levels=DEFAULT_LEVELS,
                 schemas: Optional[List[FlowSchema]] = None,
                 queue_timeout: float = 15.0):
        self.levels = {name: PriorityLevel(name, seats, queues, qlen, exempt)
                       for name, seats, queues, qlen, exempt in levels}
        self.queue_timeout = queue_timeout
        self.schemas = sorted(schemas if schemas is not None
                              else self._default_schemas(),
                              key=lambda s: s.matching_precedence)

    @staticmethod
    def _default_schemas() -> List[FlowSchema]:
        return [
            FlowSchema("system-leader-election", "leader-election", 100,
                       lambda u, v, r: r == "leases"),
            FlowSchema("kube-system-service-accounts", "workload-high", 900,
                       lambda u, v, r: u.startswith("system:")),
            FlowSchema("global-default", "global-default", 9900),
            FlowSchema("catch-all", "catch-all", 10000),
        ]

    def classify(self, user: str, verb: str, resource: str) -> PriorityLevel:
        for schema in self.schemas:
            if schema.match(user, verb, resource):
                level = self.levels.get(schema.level)
                if level is not None:
                    return level
        return self.levels["catch-all"]

    class _Ticket:
        __slots__ = ("level",)

        def __init__(self, level: PriorityLevel):
            self.level = level

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            self.level.release()

    def admit(self, user: str, verb: str, resource: str) -> "Dispatcher._Ticket":
        """Raises RejectedError (-> 429) or returns a context manager that
        holds a seat for the request's duration."""
        level = self.classify(user, verb, resource)
        level.acquire(flow_key=user, timeout=self.queue_timeout)
        return self._Ticket(level)

    def stats(self) -> dict:
        return {name: lvl.stats() for name, lvl in self.levels.items()}
