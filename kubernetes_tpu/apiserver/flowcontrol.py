"""API Priority & Fairness.

Reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol —
FlowSchemas classify requests into PriorityLevels; each level runs a
fair queueing system (fairqueuing/queueset/queueset.go):

  - a level owns Q bounded queues and S seats
  - each flow (distinguisher: the user) is dealt a HAND of H queues by
    shuffle sharding (shufflesharding/dealer.go) and enqueues on the
    shortest queue in its hand — an elephant flow can fill at most its
    own hand while a mouse flow's hand almost surely contains an
    uncrowded queue
  - seats dispatch round-robin across non-empty queues, one request
    per queue per turn — the fairness that keeps one noisy client from
    starving a peer at the same level (the upstream virtual-time WFQ
    reduces to this when all requests cost one seat)
  - a full queue or a timed-out wait is a 429 with Retry-After

Configuration is API-object driven like the reference's apf_controller:
`bind_store()` lists+watches FlowSchema / PriorityLevelConfiguration
objects (group flowcontrol.apiserver.k8s.io) and rebuilds the dispatch
table on change; code-built defaults serve until objects exist.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections import deque
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

FLOWSCHEMAS = "flowschemas"
PRIORITYLEVELS = "prioritylevelconfigurations"

DEFAULT_LEVELS = (
    # (name, seats, queues, queue_length, exempt)
    ("exempt", 0, 0, 0, True),
    ("leader-election", 10, 16, 50, False),
    ("workload-high", 40, 128, 50, False),
    ("workload-low", 20, 128, 50, False),
    ("global-default", 20, 128, 50, False),
    ("catch-all", 5, 1, 50, False),
)


class RejectedError(Exception):
    """Surfaces as HTTP 429 Too Many Requests."""


def shuffle_shard_hand(flow_key: str, queues: int,
                       hand_size: int) -> list[int]:
    """Deal `hand_size` distinct queue indices for a flow
    (shufflesharding/dealer.go): consume the flow hash as a mixed-radix
    number; each digit picks among the not-yet-dealt queues."""
    if queues <= hand_size:
        return list(range(queues))
    entropy = int.from_bytes(
        hashlib.sha256(flow_key.encode()).digest()[:16], "big")
    hand: list[int] = []
    for i in range(hand_size):
        pick = entropy % (queues - i)
        entropy //= (queues - i)
        # map pick onto the queues not already in the hand
        for dealt in sorted(hand):
            if pick >= dealt:
                pick += 1
        hand.append(pick)
    return hand


class _Waiter:
    __slots__ = ("event", "admitted")

    def __init__(self):
        self.event = threading.Event()
        self.admitted = False


class PriorityLevel:
    def __init__(self, name: str, seats: int, queues: int = 64,
                 queue_length: int = 50, exempt: bool = False,
                 hand_size: int | None = None):
        self.name = name
        self.seats = seats
        self.exempt = exempt
        self.queue_length = queue_length
        self.queues = max(1, queues)
        self.hand_size = (max(1, min(8, self.queues)) if hand_size is None
                          else max(1, min(hand_size, self.queues)))
        self._lock = threading.Lock()
        self._queues: list[deque[_Waiter]] = [deque()
                                              for _ in range(self.queues)]
        self._rr = 0  # round-robin cursor over queues
        self._in_flight = 0
        self._waiting = 0
        # metrics
        self.dispatched = 0
        self.rejected = 0
        self.timed_out = 0

    # -- queueing core ---------------------------------------------------

    def _dispatch_locked(self) -> None:
        """Hand free seats to queued requests, one per non-empty queue
        per round-robin turn (queueset dispatching)."""
        while self._in_flight < self.seats and self._waiting > 0:
            for step in range(self.queues):
                qi = (self._rr + step) % self.queues
                if self._queues[qi]:
                    waiter = self._queues[qi].popleft()
                    self._rr = (qi + 1) % self.queues
                    self._waiting -= 1
                    self._in_flight += 1
                    self.dispatched += 1
                    waiter.admitted = True
                    waiter.event.set()
                    break
            else:
                return  # queues empty (waiting counter raced)

    def acquire(self, flow_key: str = "", timeout: float = 15.0) -> bool:
        if self.exempt:
            with self._lock:
                self.dispatched += 1
            return True
        with self._lock:
            if self._in_flight < self.seats and self._waiting == 0:
                self._in_flight += 1
                self.dispatched += 1
                return True
            # shuffle-sharded queue assignment: shortest queue in hand
            hand = shuffle_shard_hand(flow_key, self.queues,
                                      self.hand_size)
            qi = min(hand, key=lambda i: len(self._queues[i]))
            if len(self._queues[qi]) >= self.queue_length:
                self.rejected += 1
                raise RejectedError(
                    "too many queued requests for flow %r at priority "
                    "level %s" % (flow_key, self.name))
            waiter = _Waiter()
            self._queues[qi].append(waiter)
            self._waiting += 1
            # a seat may have freed while we were classifying
            self._dispatch_locked()
        if waiter.event.wait(timeout):
            return True
        with self._lock:
            if waiter.admitted:
                # dispatch won the race with the timeout
                return True
            try:
                self._queues[qi].remove(waiter)
                self._waiting -= 1
            except ValueError:
                pass
            self.timed_out += 1
        raise RejectedError("request timed out in priority level queue "
                            + self.name)

    def release(self) -> None:
        if self.exempt:
            return
        with self._lock:
            self._in_flight = max(0, self._in_flight - 1)
            self._dispatch_locked()

    def reconfigure(self, seats: int, queues: int, queue_length: int,
                    hand_size: int | None) -> None:
        """Apply a config change IN PLACE: in-flight requests hold
        tickets referencing this object, so replacing it would strand
        their seats forever.  Waiters in removed queues re-home
        round-robin; new headroom dispatches immediately."""
        with self._lock:
            self.seats = seats
            self.queue_length = queue_length
            new_n = max(1, queues)
            if new_n != self.queues:
                waiters = [w for q in self._queues for w in q]
                self._queues = [deque() for _ in range(new_n)]
                for i, w in enumerate(waiters):
                    self._queues[i % new_n].append(w)
                self.queues = new_n
                self._rr = 0
            self.hand_size = (max(1, min(8, self.queues))
                              if hand_size is None
                              else max(1, min(hand_size, self.queues)))
            self._dispatch_locked()

    def stats(self) -> dict:
        with self._lock:
            return {"in_flight": self._in_flight, "waiting": self._waiting,
                    "dispatched": self.dispatched, "rejected": self.rejected,
                    "timed_out": self.timed_out}


class FlowSchema:
    """Matches requests to a priority level (flowcontrol FlowSchema)."""

    def __init__(self, name: str, level: str, matching_precedence: int = 1000,
                 match: Optional[Callable[[str, str, str], bool]] = None):
        self.name = name
        self.level = level
        self.matching_precedence = matching_precedence
        self.match = match or (lambda user, verb, resource: True)


def _schema_from_object(obj: dict) -> FlowSchema | None:
    """Compile a stored FlowSchema object into a matcher.

    Spec shape (flowcontrol.apiserver.k8s.io/v1 FlowSchema): rules of
    {subjects: [{kind: User|Group|ServiceAccount, name}], resourceRules:
    [{verbs, resources}]}; '*' wildcards match everything."""
    spec = obj.get("spec") or {}
    level = ((spec.get("priorityLevelConfiguration") or {})
             .get("name"))
    if not level:
        return None
    rules = spec.get("rules") or []

    def match(user: str, verb: str, resource: str,
              groups: tuple[str, ...] = ()) -> bool:
        if not rules:
            return True
        for rule in rules:
            subjects = rule.get("subjects") or []
            subject_ok = not subjects
            for s in subjects:
                kind = s.get("kind")
                name = (s.get("name") or
                        (s.get("user") or {}).get("name") or
                        (s.get("group") or {}).get("name") or "")
                if kind == "User" and name in ("*", user):
                    subject_ok = True
                elif kind == "Group" and (name == "*" or name in groups):
                    subject_ok = True
                elif kind == "ServiceAccount" and user.startswith(
                        "system:serviceaccount:"):
                    sa = s.get("serviceAccount") or {}
                    want = (f"system:serviceaccount:"
                            f"{sa.get('namespace', '')}:"
                            f"{sa.get('name', '')}")
                    if sa.get("name") == "*" and user.startswith(
                            f"system:serviceaccount:"
                            f"{sa.get('namespace', '')}:"):
                        subject_ok = True
                    elif user == want:
                        subject_ok = True
            if not subject_ok:
                continue
            rrules = rule.get("resourceRules") or []
            if not rrules:
                if rule.get("nonResourceRules"):
                    # this filter only classifies RESOURCE requests — a
                    # nonResourceRules-only rule (e.g. the bootstrap
                    # /healthz 'probes' schema) must not match here
                    continue
                return True
            for rr in rrules:
                verbs = rr.get("verbs") or ["*"]
                resources = rr.get("resources") or ["*"]
                if ("*" in verbs or verb in verbs) and \
                        ("*" in resources or resource in resources):
                    return True
        return False

    fs = FlowSchema(obj.get("metadata", {}).get("name", "?"), level,
                    spec.get("matchingPrecedence", 1000))
    fs.match_with_groups = match
    fs.match = lambda u, v, r: match(u, v, r, ())
    return fs


def _level_params(obj: dict) -> tuple[str, dict] | None:
    """PriorityLevelConfiguration -> (name, PriorityLevel kwargs)."""
    spec = obj.get("spec") or {}
    name = obj.get("metadata", {}).get("name")
    if not name:
        return None
    if spec.get("type") == "Exempt":
        return name, {"seats": 0, "queues": 0, "queue_length": 0,
                      "exempt": True}
    limited = spec.get("limited") or {}
    seats = limited.get("nominalConcurrencyShares", 20)
    response = limited.get("limitResponse") or {}
    if response.get("type") == "Reject":
        # at-capacity requests 429 immediately: no queues to wait in
        return name, {"seats": seats, "queues": 1, "queue_length": 0,
                      "hand_size": 1}
    queuing = response.get("queuing") or {}
    return name, {"seats": seats,
                  "queues": queuing.get("queues", 64),
                  "queue_length": queuing.get("queueLengthLimit", 50),
                  "hand_size": queuing.get("handSize")}


class Dispatcher:
    """The WithPriorityAndFairness filter (config.go:823)."""

    def __init__(self, levels=DEFAULT_LEVELS,
                 schemas: Optional[List[FlowSchema]] = None,
                 queue_timeout: float = 15.0):
        self._lock = threading.Lock()
        self.levels = {name: PriorityLevel(name, seats, queues, qlen, exempt)
                       for name, seats, queues, qlen, exempt in levels}
        self.queue_timeout = queue_timeout
        self.schemas = sorted(schemas if schemas is not None
                              else self._default_schemas(),
                              key=lambda s: s.matching_precedence)
        self._stop = threading.Event()
        self._watch_thread: threading.Thread | None = None

    @staticmethod
    def _default_schemas() -> List[FlowSchema]:
        return [
            FlowSchema("system-leader-election", "leader-election", 100,
                       lambda u, v, r: r == "leases"),
            FlowSchema("kube-system-service-accounts", "workload-high", 900,
                       lambda u, v, r: u.startswith("system:")),
            FlowSchema("global-default", "global-default", 9900),
            FlowSchema("catch-all", "catch-all", 10000),
        ]

    # -- API-object configuration (apf_controller.go) --------------------

    def bind_store(self, store) -> None:
        """Drive configuration from stored FlowSchema /
        PriorityLevelConfiguration objects: list now, watch for changes.
        Stored objects REPLACE the code defaults for their name;
        deleting one reverts to the default.  The watch resumes from
        the reload's own list revision — an object written between the
        two would otherwise be lost to both."""
        self._store = store
        self._defaults = {name: dict(seats=seats, queues=queues,
                                     queue_length=qlen, exempt=exempt)
                          for name, seats, queues, qlen, exempt
                          in DEFAULT_LEVELS}
        since_rv = self._reload()
        self._watch_thread = threading.Thread(
            target=self._watch_loop, args=(since_rv,),
            name="apf-config-watch", daemon=True)
        self._watch_thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _reload(self) -> int:
        plcs, rv1 = self._store.list(PRIORITYLEVELS)
        schemas_objs, rv2 = self._store.list(FLOWSCHEMAS)
        desired: dict[str, dict] = {
            name: dict(params) for name, params in self._defaults.items()}
        for obj in plcs:
            got = _level_params(obj)
            if got is not None:
                desired[got[0]] = got[1]
        with self._lock:
            for name, params in desired.items():
                existing = self.levels.get(name)
                if existing is not None and not existing.exempt \
                        and not params.get("exempt"):
                    # reconfigure IN PLACE: live tickets reference this
                    # object, so swapping it would strand their seats
                    existing.reconfigure(
                        params["seats"], params["queues"],
                        params["queue_length"], params.get("hand_size"))
                elif existing is None or bool(params.get("exempt")) \
                        != existing.exempt:
                    self.levels[name] = PriorityLevel(name, **params)
            for name in [n for n in self.levels if n not in desired]:
                del self.levels[name]  # PLC deleted, no default: gone
            stored = []
            for obj in schemas_objs:
                fs = _schema_from_object(obj)
                if fs is not None and fs.level in self.levels:
                    stored.append(fs)
            names = {fs.name for fs in stored}
            kept = [s for s in self._default_schemas()
                    if s.name not in names]
            self.schemas = sorted(stored + kept,
                                  key=lambda s: s.matching_precedence)
        return min(rv1, rv2)

    def _watch_loop(self, since_rv: int) -> None:
        watches = [self._store.watch(FLOWSCHEMAS, since_rv=since_rv),
                   self._store.watch(PRIORITYLEVELS, since_rv=since_rv)]
        try:
            while not self._stop.is_set():
                changed = False
                for w in watches:
                    ev = w.next(timeout=0.5)
                    while ev is not None:
                        changed = True
                        ev = w.next(timeout=0.0)
                if changed:
                    self._reload()
        finally:
            for w in watches:
                w.stop()

    # -- request path ----------------------------------------------------

    def classify(self, user: str, verb: str, resource: str,
                 groups: tuple[str, ...] = ()) -> PriorityLevel:
        with self._lock:
            schemas = list(self.schemas)
            levels = dict(self.levels)
        for schema in schemas:
            matcher = getattr(schema, "match_with_groups", None)
            hit = (matcher(user, verb, resource, groups) if matcher
                   else schema.match(user, verb, resource))
            if hit:
                level = levels.get(schema.level)
                if level is not None:
                    return level
        return levels["catch-all"]

    class _Ticket:
        __slots__ = ("level",)

        def __init__(self, level: PriorityLevel):
            self.level = level

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            self.level.release()

    def admit(self, user: str, verb: str, resource: str,
              groups: tuple[str, ...] = ()) -> "Dispatcher._Ticket":
        """Raises RejectedError (-> 429) or returns a context manager that
        holds a seat for the request's duration.  The flow
        distinguisher is the user (FlowDistinguisherMethodByUser)."""
        level = self.classify(user, verb, resource, groups)
        level.acquire(flow_key=user, timeout=self.queue_timeout)
        return self._Ticket(level)

    def stats(self) -> dict:
        with self._lock:
            levels = dict(self.levels)
        return {name: lvl.stats() for name, lvl in levels.items()}
