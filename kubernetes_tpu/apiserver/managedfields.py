"""Server-side apply: managedFields tracking + conflict detection.

Reference semantics (not implementation):
  staging/src/k8s.io/apimachinery/pkg/util/managedfields/ — every write
    records which *field manager* owns which fields, as a fieldsV1 trie
    in metadata.managedFields;
  sigs.k8s.io/structured-merge-diff — apply = three-way merge driven by
    ownership: an Apply operation (PATCH application/apply-patch+yaml)
    sets exactly the fields in the applied config, REMOVES fields the
    same manager applied before but dropped, and CONFLICTS (409) when it
    would overwrite a field another manager owns with a different value
    — unless force=true steals ownership;
  Update operations (PUT / other PATCH) take ownership of every field
    they change (last-write-wins, no conflicts).

Design: ownership is a set of *leaf paths*.  A path step is one of
  ("f", key)       map field
  ("k", keyjson)   associative-list element, keyed like k:{"name":"c1"}
                   by the strategic merge key (patch.STRATEGIC_MERGE_KEYS)
  ("v", valjson)   set-style scalar list element (e.g. finalizers)
Lists without a merge key are atomic: the whole list is one leaf.  The
wire form in metadata.managedFields[].fieldsV1 is the standard trie
("f:spec": {"f:replicas": {}}), converted losslessly to/from leaf sets.

The merge itself operates on the flattened form: conflict checks compare
applied values with live values at the intersection of leaf sets, and
object construction sets/deletes values path by path.  That makes every
rule (removal, co-ownership, stealing) a set operation — much simpler to
verify than a recursive three-way merge, at the cost of re-walking the
object per path (objects here are control-plane sized, not data).
"""

from __future__ import annotations

import copy
import json
import time
from typing import Any

from ..store.kv import ConflictError
from .patch import STRATEGIC_MERGE_KEYS

APPLY_CONTENT_TYPE = "application/apply-patch+yaml"

# metadata bookkeeping fields that are never owned by a manager
_UNOWNED_META = frozenset({
    "name", "namespace", "uid", "resourceVersion", "generation",
    "creationTimestamp", "deletionTimestamp", "managedFields", "selfLink",
})


class ApplyConflict(ConflictError):
    """Another manager owns a field the apply wants to change (409).
    A ConflictError subclass so both transports (LocalClient in-process,
    HTTPClient via the 409 mapping) surface the same exception type."""

    def __init__(self, conflicts: list[tuple[str, tuple]]):
        self.conflicts = conflicts  # [(manager, path), ...]
        names = sorted({m for m, _ in conflicts})
        paths = ", ".join(path_str(p) for _, p in conflicts[:5])
        super().__init__(
            f"apply conflicts with manager(s) {names} on: {paths}"
            + (" ..." if len(conflicts) > 5 else ""))


def path_str(path: tuple) -> str:
    out = []
    for kind, key in path:
        if kind == "f":
            out.append(f".{key}")
        elif kind == "k":
            out.append(f"[{key}]")
        else:
            out.append(f"[={key}]")
    return "".join(out) or "."


# -- flatten an object to leaf paths -------------------------------------

def leaves_of(obj: dict, *, _top: bool = True) -> set[tuple]:
    """All leaf paths present in obj (ownership universe of a write)."""
    acc: set[tuple] = set()
    _walk(obj, (), acc, top=_top)
    return acc


def _walk(val: Any, path: tuple, acc: set[tuple], top: bool = False,
          field: str = "") -> None:
    if isinstance(val, dict):
        items = val.items()
        for k, v in items:
            if top and k in ("apiVersion", "kind"):
                continue
            if path == (("f", "metadata"),) and k in _UNOWNED_META:
                continue
            _walk(v, path + (("f", k),), acc, field=k)
        if not val and path:
            acc.add(path)  # empty map: owned as a unit
        return
    if isinstance(val, list):
        mk = STRATEGIC_MERGE_KEYS.get(field, "__atomic__")
        if mk == "__atomic__":
            acc.add(path)  # atomic list: one leaf
            return
        if mk is None:  # set of scalars
            for x in val:
                acc.add(path + (("v", json.dumps(x, sort_keys=True)),))
            if not val and path:
                acc.add(path)
            return
        for item in val:
            if not isinstance(item, dict) or mk not in item:
                acc.add(path)  # unkeyable element: fall back to atomic
                return
            kj = json.dumps({mk: item[mk]}, sort_keys=True)
            _walk(item, path + (("k", kj),), acc, field=field)
        if not val and path:
            acc.add(path)
        return
    acc.add(path)  # scalar


# -- value access by path -------------------------------------------------

_MISSING = object()


def get_at(obj: Any, path: tuple) -> Any:
    cur = obj
    for kind, key in path:
        if kind == "f":
            if not isinstance(cur, dict) or key not in cur:
                return _MISSING
            cur = cur[key]
        elif kind == "k":
            want = json.loads(key)
            if not isinstance(cur, list):
                return _MISSING
            for item in cur:
                if isinstance(item, dict) and all(
                        item.get(k) == v for k, v in want.items()):
                    cur = item
                    break
            else:
                return _MISSING
        else:  # v: membership
            want = json.loads(key)
            if not isinstance(cur, list) or want not in cur:
                return _MISSING
            cur = want
    return cur


def set_at(obj: dict, path: tuple, value: Any) -> None:
    """Create containers along `path` and set the leaf to `value`."""
    cur = obj
    for i, (kind, key) in enumerate(path):
        last = i == len(path) - 1
        if kind == "f":
            if last:
                cur[key] = value
                return
            nkind = path[i + 1][0]
            nxt = cur.get(key)
            if nkind == "f":
                if not isinstance(nxt, dict):
                    nxt = cur[key] = {}
            else:
                if not isinstance(nxt, list):
                    nxt = cur[key] = []
            cur = nxt
        elif kind == "k":
            want = json.loads(key)
            for item in cur:
                if isinstance(item, dict) and all(
                        item.get(k) == v for k, v in want.items()):
                    break
            else:
                item = dict(want)
                cur.append(item)
            if last:
                # replace the element wholesale (value carries the key)
                item.clear()
                item.update(value)
                return
            cur = item
        else:  # v: ensure membership
            want = json.loads(key)
            if want not in cur:
                cur.append(want)
            return


def delete_at(obj: dict, path: tuple) -> None:
    if not path:
        return
    parent = get_at(obj, path[:-1]) if len(path) > 1 else obj
    if parent is _MISSING:
        return
    kind, key = path[-1]
    if kind == "f":
        if isinstance(parent, dict):
            parent.pop(key, None)
    elif kind == "k":
        want = json.loads(key)
        if isinstance(parent, list):
            parent[:] = [it for it in parent
                         if not (isinstance(it, dict) and all(
                             it.get(k) == v for k, v in want.items()))]
    else:
        want = json.loads(key)
        if isinstance(parent, list) and want in parent:
            parent.remove(want)


# -- fieldsV1 wire form ---------------------------------------------------

def leaves_to_trie(leaves: set[tuple]) -> dict:
    root: dict = {}
    for path in sorted(leaves):
        node = root
        for kind, key in path:
            node = node.setdefault(f"{kind}:{key}", {})
        node["."] = {}
    return root


def trie_to_leaves(trie: dict, prefix: tuple = ()) -> set[tuple]:
    acc: set[tuple] = set()
    for k, sub in trie.items():
        if k == ".":
            if prefix:
                acc.add(prefix)
            continue
        kind, _, key = k.partition(":")
        acc |= trie_to_leaves(sub, prefix + ((kind, key),))
    return acc


# -- managedFields entries ------------------------------------------------

def read_managers(obj: dict) -> dict[tuple[str, str], set[tuple]]:
    """{(manager, operation): leaf set} from metadata.managedFields."""
    out = {}
    for entry in (obj.get("metadata") or {}).get("managedFields") or []:
        key = (entry.get("manager", ""), entry.get("operation", "Update"))
        out[key] = trie_to_leaves(entry.get("fieldsV1") or {})
    return out


def write_managers(obj: dict, managers: dict[tuple[str, str], set[tuple]],
                   now: float | None = None) -> None:
    entries = []
    for (mgr, op), leaves in sorted(managers.items()):
        if not leaves:
            continue
        entries.append({"manager": mgr, "operation": op,
                        "apiVersion": obj.get("apiVersion", "v1"),
                        "time": now if now is not None else time.time(),
                        "fieldsV1": leaves_to_trie(leaves)})
    md = obj.setdefault("metadata", {})
    if entries:
        md["managedFields"] = entries
    else:
        md.pop("managedFields", None)


# -- the two write paths --------------------------------------------------

def apply_merge(live: dict | None, applied: dict, manager: str,
                force: bool = False) -> dict:
    """Three-way apply (the SSA PATCH verb).  Returns the new object;
    raises ApplyConflict unless force steals the contested fields.

    live=None means create-on-apply: the applied config becomes the
    object and the manager owns everything it set.
    """
    applied_leaves = leaves_of(applied)
    if live is None:
        new = copy.deepcopy(applied)
        write_managers(new, {(manager, "Apply"): applied_leaves})
        return new

    managers = read_managers(live)
    mine_key = (manager, "Apply")
    mine_prev = managers.get(mine_key, set())

    # conflicts: another manager owns a leaf I'm applying with a new value
    conflicts = []
    for (mgr, op), theirs in managers.items():
        if mgr == manager:
            continue
        for path in applied_leaves & theirs:
            want = get_at(applied, path)
            have = get_at(live, path)
            if want != have:
                conflicts.append(((mgr, op), path))
    if conflicts and not force:
        raise ApplyConflict([(m, p) for (m, _), p in
                             sorted(conflicts, key=lambda c: c[1])])
    for mkey, path in conflicts:  # force: steal ownership
        managers[mkey].discard(path)

    new = copy.deepcopy(live)
    # removal: fields I applied before, dropped now, and nobody else owns
    others_all = set()
    for key, theirs in managers.items():
        if key != mine_key:
            others_all |= theirs
    # delete deepest-first so children vanish before their parents are
    # (possibly) deleted as emptied containers
    for path in sorted(mine_prev - applied_leaves, key=len, reverse=True):
        if path not in others_all:
            delete_at(new, path)
    # set every applied leaf
    for path in sorted(applied_leaves, key=len):
        val = get_at(applied, path)
        if val is _MISSING:
            continue
        if val == {} or val == []:
            # an applied EMPTY container claims the container's
            # existence, not its (possibly co-owned) contents
            if get_at(new, path) is _MISSING:
                set_at(new, path, val)
            continue
        set_at(new, path, val)
    managers[mine_key] = applied_leaves
    write_managers(new, managers)
    return new


def track_update(live: dict | None, new: dict, manager: str) -> None:
    """Ownership bookkeeping for a non-apply write (PUT / RFC patch):
    the manager takes every leaf it changed or added; leaves that
    disappeared stop being owned by anyone (managedfields Update op).
    Mutates `new` in place."""
    managers = read_managers(live) if live is not None else {}
    new_leaves = leaves_of(new)
    if live is not None:
        old_leaves = leaves_of(live)
        changed = {p for p in new_leaves
                   if get_at(new, p) != get_at(live, p)}
        removed = old_leaves - new_leaves
    else:
        changed = set(new_leaves)
        removed = set()
    if changed or removed:
        for key, theirs in managers.items():
            theirs -= changed
            theirs -= removed
        mine = managers.setdefault((manager, "Update"), set())
        mine |= changed
    write_managers(new, managers)
