"""Structural OpenAPI v2 schemas for the load-bearing built-in kinds.

The reference aggregates generated per-field swagger docs for every type
(kube-openapi over `staging/src/k8s.io/api/*/types.go` comment docs);
here the hot kinds carry hand-maintained structural schemas — enough for
`kubectl explain`, client validation, and discovery tooling to walk real
field trees with descriptions.  Kinds not listed fall back to the
skeleton definition (discovery.py openapi_v2 add()).
"""

from __future__ import annotations


def _obj(description: str, properties: dict | None = None,
         required: list[str] | None = None, gvk: list[dict] | None = None,
         additional=None) -> dict:
    d: dict = {"type": "object", "description": description}
    if properties:
        d["properties"] = properties
    if required:
        d["required"] = required
    if gvk:
        d["x-kubernetes-group-version-kind"] = gvk
    if additional is not None:
        d["additionalProperties"] = additional
    return d


def _s(description: str) -> dict:
    return {"type": "string", "description": description}


def _i(description: str) -> dict:
    return {"type": "integer", "description": description}


def _b(description: str) -> dict:
    return {"type": "boolean", "description": description}


def _arr(items: dict, description: str) -> dict:
    return {"type": "array", "items": items, "description": description}


def _ref(key: str, description: str = "") -> dict:
    d: dict = {"$ref": f"#/definitions/{key}"}
    if description:
        d["description"] = description
    return d


def _map(description: str) -> dict:
    return _obj(description, additional={"type": "string"})


# -- shared sub-definitions ----------------------------------------------

DEFINITIONS: dict[str, dict] = {
    "v1.ObjectMeta": _obj(
        "Standard object metadata (apimachinery/pkg/apis/meta/v1).",
        {
            "name": _s("Unique name within a namespace. Immutable."),
            "namespace": _s("Namespace scoping the object; 'default' "
                            "when unset on namespaced resources."),
            "labels": _map("String keys/values used by selectors."),
            "annotations": _map("Arbitrary non-identifying metadata."),
            "uid": _s("System-generated unique id, stable for the "
                      "object's lifetime."),
            "resourceVersion": _s("Opaque version for optimistic "
                                  "concurrency and watch resumption."),
            "creationTimestamp": _s("Server-assigned RFC3339 creation "
                                    "time."),
            "deletionTimestamp": _s("Set when deletion is requested; "
                                    "the object is terminating."),
            "generation": _i("Sequence number incremented on spec "
                             "changes."),
            "ownerReferences": _arr(
                _obj("Owner of this object (controller GC roots).", {
                    "apiVersion": _s("Owner apiVersion."),
                    "kind": _s("Owner kind."),
                    "name": _s("Owner name."),
                    "uid": _s("Owner uid."),
                    "controller": _b("True when the managing "
                                     "controller."),
                    "blockOwnerDeletion": _b(
                        "Owner cannot be deleted until this "
                        "dependent is gone (foreground GC)."),
                }),
                "Objects depended on by this one; GC deletes the object "
                "when all owners are gone."),
            "finalizers": _arr(_s("Finalizer key."),
                               "Must be emptied before deletion "
                               "completes."),
            "managedFields": _arr(
                _obj("Field ownership entry (server-side apply)."),
                "Per-manager field ownership used by server-side "
                "apply conflict detection."),
        }),
    "v1.ResourceRequirements": _obj(
        "Compute resource requests/limits (pkg/api/v1/resource).",
        {
            "requests": _map("Minimum resources required: cpu "
                             "(milli-units, e.g. '250m'), memory "
                             "(e.g. '256Mi'), ephemeral-storage, and "
                             "extended resources."),
            "limits": _map("Maximum resources allowed; same keys as "
                           "requests."),
        }),
    "v1.ContainerPort": _obj(
        "Network port exposed by a container.",
        {
            "name": _s("IANA_SVC_NAME, unique within the pod."),
            "containerPort": _i("Port number on the pod's IP."),
            "hostPort": _i("Port on the host node; constrains "
                           "scheduling (NodePorts plugin)."),
            "hostIP": _s("Host IP to bind the hostPort to."),
            "protocol": _s("TCP, UDP or SCTP; defaults to TCP."),
        }, required=["containerPort"]),
    "v1.EnvVar": _obj(
        "Environment variable in a container.",
        {
            "name": _s("Variable name."),
            "value": _s("Literal value."),
            "valueFrom": _obj("Source for the value (fieldRef, "
                              "configMapKeyRef, secretKeyRef)."),
        }, required=["name"]),
    "v1.VolumeMount": _obj(
        "Mount of a pod volume into a container.",
        {
            "name": _s("Matches a pod volume name."),
            "mountPath": _s("Path within the container."),
            "readOnly": _b("Mounted read-only when true."),
            "subPath": _s("Sub-path within the volume."),
        }, required=["name", "mountPath"]),
    "v1.Probe": _obj(
        "Health check performed against a container "
        "(kubelet prober).",
        {
            "exec": _obj("Command probe: exit 0 == healthy.", {
                "command": _arr(_s("argv element."),
                                "Command to run in the container."),
            }),
            "httpGet": _obj("HTTP probe: 2xx/3xx == healthy.", {
                "path": _s("Request path."),
                "port": _i("Port to connect to."),
                "host": _s("Host header override."),
                "scheme": _s("HTTP or HTTPS."),
            }),
            "tcpSocket": _obj("TCP probe: connect success == healthy.", {
                "port": _i("Port to connect to."),
            }),
            "initialDelaySeconds": _i("Delay before the first probe."),
            "periodSeconds": _i("Probe interval; default 10s."),
            "timeoutSeconds": _i("Per-probe timeout; default 1s."),
            "successThreshold": _i("Consecutive successes to be "
                                   "healthy; default 1."),
            "failureThreshold": _i("Consecutive failures to be "
                                   "unhealthy; default 3."),
        }),
    "v1.Container": _obj(
        "A single container within a pod (core/v1 Container).",
        {
            "name": _s("DNS_LABEL, unique within the pod. Immutable."),
            "image": _s("Container image reference."),
            "command": _arr(_s("argv element."),
                            "Entrypoint override (not run in a shell)."),
            "args": _arr(_s("argument."), "Arguments to the entrypoint."),
            "workingDir": _s("Working directory."),
            "ports": _arr(_ref("v1.ContainerPort"),
                          "Ports exposed by the container; hostPort "
                          "entries constrain scheduling."),
            "env": _arr(_ref("v1.EnvVar"), "Environment variables."),
            "resources": _ref("v1.ResourceRequirements",
                              "Requests drive scheduling (NodeResourcesFit"
                              "); limits drive QoS class."),
            "volumeMounts": _arr(_ref("v1.VolumeMount"),
                                 "Pod volumes mounted into this "
                                 "container."),
            "livenessProbe": _ref("v1.Probe",
                                  "Failure restarts the container."),
            "readinessProbe": _ref("v1.Probe",
                                   "Failure removes the pod from "
                                   "service endpoints."),
            "startupProbe": _ref("v1.Probe",
                                 "Gates liveness/readiness until "
                                 "first success."),
            "imagePullPolicy": _s("Always, IfNotPresent or Never."),
            "securityContext": _obj("Per-container security options."),
        }, required=["name"]),
    "v1.Toleration": _obj(
        "Marks the pod as tolerating a matching node taint "
        "(TaintToleration plugin).",
        {
            "key": _s("Taint key; empty + Exists matches all."),
            "operator": _s("Exists or Equal (default Equal)."),
            "value": _s("Taint value to equal."),
            "effect": _s("NoSchedule, PreferNoSchedule or NoExecute; "
                         "empty matches all."),
            "tolerationSeconds": _i("For NoExecute: seconds the pod "
                                    "stays bound after the taint "
                                    "appears."),
        }),
    "v1.LabelSelector": _obj(
        "Label query over a set of objects "
        "(apimachinery LabelSelector).",
        {
            "matchLabels": _map("Exact-match key/value requirements, "
                                "ANDed."),
            "matchExpressions": _arr(
                _obj("Set-based requirement.", {
                    "key": _s("Label key."),
                    "operator": _s("In, NotIn, Exists or "
                                   "DoesNotExist."),
                    "values": _arr(_s("value."),
                                   "Values for In/NotIn."),
                }),
                "Set-based requirements, ANDed with matchLabels."),
        }),
    "v1.TopologySpreadConstraint": _obj(
        "Even-spread constraint over topology domains "
        "(PodTopologySpread plugin).",
        {
            "maxSkew": _i("Max allowed difference in matching-pod "
                          "counts between domains."),
            "topologyKey": _s("Node label key defining the domains "
                              "(e.g. topology.kubernetes.io/zone)."),
            "whenUnsatisfiable": _s("DoNotSchedule (hard) or "
                                    "ScheduleAnyway (scoring)."),
            "labelSelector": _ref("v1.LabelSelector",
                                  "Pods counted per domain."),
        }, required=["maxSkew", "topologyKey", "whenUnsatisfiable"]),
    "v1.Affinity": _obj(
        "Scheduling affinity rules (NodeAffinity / InterPodAffinity "
        "plugins).",
        {
            "nodeAffinity": _obj("Node label constraints.", {
                "requiredDuringSchedulingIgnoredDuringExecution": _obj(
                    "Hard node selector terms (filter)."),
                "preferredDuringSchedulingIgnoredDuringExecution": _arr(
                    _obj("Weighted preference (score)."),
                    "Soft node preferences."),
            }),
            "podAffinity": _obj("Attract toward nodes/domains running "
                                "matching pods."),
            "podAntiAffinity": _obj("Repel from nodes/domains running "
                                    "matching pods."),
        }),
    "v1.PodSpec": _obj(
        "Desired pod behavior (core/v1 PodSpec).",
        {
            "containers": _arr(_ref("v1.Container"),
                               "Containers in the pod; at least one. "
                               "Cannot be added/removed in place."),
            "initContainers": _arr(_ref("v1.Container"),
                                   "Run to completion, in order, "
                                   "before containers start."),
            "nodeName": _s("Node the pod is bound to; set by the "
                           "scheduler via the binding subresource."),
            "nodeSelector": _map("Hard node-label requirements "
                                 "(NodeAffinity filter)."),
            "affinity": _ref("v1.Affinity"),
            "tolerations": _arr(_ref("v1.Toleration"),
                                "Taints this pod tolerates."),
            "topologySpreadConstraints": _arr(
                _ref("v1.TopologySpreadConstraint"),
                "Even-spread constraints over topology domains."),
            "schedulerName": _s("Profile that schedules this pod; "
                                "default-scheduler when unset."),
            "priority": _i("Resolved priority value (admission fills "
                           "it from priorityClassName)."),
            "priorityClassName": _s("PriorityClass to resolve "
                                    "priority from."),
            "preemptionPolicy": _s("PreemptLowerPriority (default) or "
                                   "Never."),
            "restartPolicy": _s("Always, OnFailure or Never."),
            "terminationGracePeriodSeconds": _i(
                "Seconds allowed for graceful shutdown; default 30."),
            "serviceAccountName": _s("ServiceAccount for API "
                                     "credentials."),
            "volumes": _arr(_obj("Pod volume definition."),
                            "Volumes mountable by containers."),
            "hostNetwork": _b("Use the host's network namespace."),
            "overhead": _map("Resource overhead of the pod sandbox "
                             "(RuntimeClass)."),
        }, required=["containers"]),
    "v1.PodStatus": _obj(
        "Most recently observed pod state (written by kubelet and "
        "scheduler).",
        {
            "phase": _s("Pending, Running, Succeeded, Failed or "
                        "Unknown."),
            "conditions": _arr(
                _obj("Condition entry.", {
                    "type": _s("PodScheduled, Ready, Initialized, "
                               "ContainersReady."),
                    "status": _s("True, False or Unknown."),
                    "reason": _s("Machine-readable reason (e.g. "
                                 "Unschedulable)."),
                    "message": _s("Human-readable detail."),
                }),
                "Current service state conditions."),
            "podIP": _s("Pod's primary IP, assigned at sandbox "
                        "creation."),
            "hostIP": _s("IP of the node the pod runs on."),
            "containerStatuses": _arr(
                _obj("Per-container runtime status."),
                "Status of each container in spec.containers."),
            "nominatedNodeName": _s("Node nominated by preemption; "
                                    "scheduler tries it first."),
            "startTime": _s("Time the kubelet acknowledged the pod."),
            "qosClass": _s("Guaranteed, Burstable or BestEffort."),
        }),
    "v1.NodeStatus": _obj(
        "Most recently observed node state (kubelet status loop).",
        {
            "capacity": _map("Total resources: cpu, memory, pods, "
                             "ephemeral-storage, extended resources."),
            "allocatable": _map("Resources available for pods "
                                "(capacity minus reserved); the "
                                "scheduler fits against these."),
            "conditions": _arr(
                _obj("Node condition.", {
                    "type": _s("Ready, MemoryPressure, DiskPressure, "
                               "PIDPressure, NetworkUnavailable."),
                    "status": _s("True, False or Unknown."),
                    "reason": _s("Machine-readable reason."),
                }),
                "Observed conditions; Ready gates scheduling."),
            "addresses": _arr(_obj("Node address.", {
                "type": _s("InternalIP, ExternalIP or Hostname."),
                "address": _s("The address."),
            }), "Reachable addresses."),
            "nodeInfo": _obj("Static node info (kubelet version, OS, "
                             "architecture)."),
            "images": _arr(_obj("Image present on the node."),
                           "Container images on this node (image "
                           "locality scoring)."),
        }),
    "v1.Taint": _obj(
        "Repels pods that do not tolerate it (node.spec.taints).",
        {
            "key": _s("Taint key."),
            "value": _s("Taint value."),
            "effect": _s("NoSchedule, PreferNoSchedule or NoExecute."),
            "timeAdded": _s("When added (NoExecute only)."),
        }, required=["key", "effect"]),
    "v1.ServicePort": _obj(
        "Port exposed by a Service.",
        {
            "name": _s("Name, unique in the service; required when "
                       "multiple ports."),
            "port": _i("Port exposed by the service."),
            "targetPort": _i("Port (or named port) on the backend "
                             "pods."),
            "nodePort": _i("Node-wide port for NodePort/LoadBalancer "
                           "services (allocated from the node port "
                           "range when unset)."),
            "protocol": _s("TCP, UDP or SCTP; default TCP."),
        }, required=["port"]),
    "v1.PodTemplateSpec": _obj(
        "Pod template stamped out by workload controllers.",
        {
            "metadata": _ref("v1.ObjectMeta",
                             "Labels here must satisfy the parent's "
                             "selector."),
            "spec": _ref("v1.PodSpec"),
        }),
}


# -- top-level kinds ------------------------------------------------------

def _kind(gv: str, kind: str, description: str, spec: dict | None,
          status: dict | None, extra: dict | None = None) -> dict:
    group, _, version = gv.rpartition("/")
    props = {
        "apiVersion": _s("Schema version of this representation."),
        "kind": _s("REST resource this object represents."),
        "metadata": _ref("v1.ObjectMeta"),
    }
    if spec is not None:
        props["spec"] = spec
    if status is not None:
        props["status"] = status
    if extra:
        props.update(extra)
    return _obj(description, props,
                gvk=[{"group": group, "version": version, "kind": kind}])


KIND_SCHEMAS: dict[str, dict] = {
    "v1.Pod": _kind(
        "v1", "Pod",
        "A group of containers scheduled onto one node and sharing its "
        "network/storage context (ref pkg/apis/core/types.go Pod).",
        _ref("v1.PodSpec", "Desired behavior."),
        _ref("v1.PodStatus", "Observed state.")),
    "v1.Node": _kind(
        "v1", "Node",
        "A worker machine; pods are bound to nodes by the scheduler.",
        _obj("Node configuration.", {
            "unschedulable": _b("Excludes the node from scheduling "
                                "(kubectl cordon)."),
            "taints": _arr(_ref("v1.Taint"),
                           "Taints repelling non-tolerating pods."),
            "podCIDR": _s("Pod IP range assigned to the node."),
            "providerID": _s("Cloud provider node id."),
        }),
        _ref("v1.NodeStatus", "Observed state.")),
    "v1.Service": _kind(
        "v1", "Service",
        "Named abstraction over a set of pods: a virtual IP and port "
        "list load-balanced to selected backends (kube-proxy).",
        _obj("Service behavior.", {
            "selector": _map("Pods with these labels back the "
                             "service; endpoints are derived "
                             "continuously."),
            "ports": _arr(_ref("v1.ServicePort"),
                          "Exposed ports."),
            "type": _s("ClusterIP, NodePort, LoadBalancer or "
                       "ExternalName."),
            "clusterIP": _s("Virtual IP; allocated when unset; "
                            "'None' for headless services."),
            "sessionAffinity": _s("None or ClientIP (sticky "
                                  "backends)."),
            "externalName": _s("CNAME target for ExternalName "
                               "services."),
        }),
        _obj("Observed state.", {
            "loadBalancer": _obj("Ingress points of the external "
                                 "load balancer."),
        })),
    "v1.Namespace": _kind(
        "v1", "Namespace",
        "Scope for names and policy; namespaced objects live inside "
        "exactly one.",
        _obj("Behavior.", {
            "finalizers": _arr(_s("finalizer."),
                               "Must empty before the namespace is "
                               "fully deleted."),
        }),
        _obj("Lifecycle state.", {
            "phase": _s("Active or Terminating."),
        })),
    "v1.ConfigMap": _kind(
        "v1", "ConfigMap",
        "Non-secret configuration as key/value pairs, consumable as "
        "env vars or volumes.",
        None, None,
        extra={"data": _map("UTF-8 configuration entries."),
               "binaryData": _map("Base64 binary entries."),
               "immutable": _b("Data cannot change when true.")}),
    "v1.Secret": _kind(
        "v1", "Secret",
        "Sensitive data (tokens, keys, certs); base64-encoded at rest.",
        None, None,
        extra={"data": _map("Base64-encoded entries."),
               "stringData": _map("Write-only plain entries, merged "
                                  "into data."),
               "type": _s("Opaque, kubernetes.io/service-account-token, "
                          "kubernetes.io/tls, ...")}),
    "v1.Event": _kind(
        "v1", "Event",
        "A report of something that happened to an object (scheduler "
        "decisions, kubelet lifecycle, controller actions).",
        None, None,
        extra={
            "involvedObject": _obj("The object this event is about.", {
                "kind": _s("Kind."), "namespace": _s("Namespace."),
                "name": _s("Name."), "uid": _s("UID."),
            }),
            "reason": _s("Short machine-readable reason (e.g. "
                         "Scheduled, FailedScheduling)."),
            "message": _s("Human-readable description."),
            "type": _s("Normal or Warning."),
            "count": _i("Times this event occurred (aggregation)."),
            "source": _obj("Reporting component.", {
                "component": _s("e.g. default-scheduler."),
                "host": _s("Node name."),
            }),
        }),
    "apps/v1.Deployment": _kind(
        "apps/v1", "Deployment",
        "Declarative updates for ReplicaSets: rolling upgrades, "
        "rollback, pause/resume (pkg/controller/deployment).",
        _obj("Desired state.", {
            "replicas": _i("Desired pod count; default 1."),
            "selector": _ref("v1.LabelSelector",
                             "Must match template labels. Immutable."),
            "template": _ref("v1.PodTemplateSpec"),
            "strategy": _obj("Replacement strategy.", {
                "type": _s("RollingUpdate (default) or Recreate."),
                "rollingUpdate": _obj("Rolling update bounds.", {
                    "maxUnavailable": _i("Pods that may be down "
                                         "during update."),
                    "maxSurge": _i("Pods over desired during "
                                   "update."),
                }),
            }),
            "minReadySeconds": _i("Seconds a new pod must be ready "
                                  "to count as available."),
            "revisionHistoryLimit": _i("Old ReplicaSets retained for "
                                       "rollback; default 10."),
            "paused": _b("Rollouts suspended when true."),
        }),
        _obj("Observed state.", {
            "replicas": _i("Total pods tracked."),
            "updatedReplicas": _i("Pods at the current template."),
            "readyReplicas": _i("Ready pods."),
            "availableReplicas": _i("Ready for minReadySeconds."),
            "observedGeneration": _i("Generation acted on."),
            "conditions": _arr(_obj("Deployment condition."),
                               "Available / Progressing state."),
        })),
    "apps/v1.ReplicaSet": _kind(
        "apps/v1", "ReplicaSet",
        "Maintains a stable set of replica pods "
        "(pkg/controller/replicaset).",
        _obj("Desired state.", {
            "replicas": _i("Desired pod count."),
            "selector": _ref("v1.LabelSelector"),
            "template": _ref("v1.PodTemplateSpec"),
            "minReadySeconds": _i("Readiness dwell before counting "
                                  "available."),
        }),
        _obj("Observed state.", {
            "replicas": _i("Current pod count."),
            "readyReplicas": _i("Ready pods."),
            "availableReplicas": _i("Available pods."),
            "fullyLabeledReplicas": _i("Pods matching all template "
                                       "labels."),
            "observedGeneration": _i("Generation acted on."),
        })),
    "apps/v1.StatefulSet": _kind(
        "apps/v1", "StatefulSet",
        "Ordered, identity-preserving replicas with stable names "
        "(pkg/controller/statefulset).",
        _obj("Desired state.", {
            "replicas": _i("Desired pod count."),
            "selector": _ref("v1.LabelSelector"),
            "template": _ref("v1.PodTemplateSpec"),
            "serviceName": _s("Headless service owning the pod DNS "
                              "identities."),
            "podManagementPolicy": _s("OrderedReady (default) or "
                                      "Parallel."),
            "updateStrategy": _obj("RollingUpdate (partitioned) or "
                                   "OnDelete."),
        }),
        _obj("Observed state.", {
            "replicas": _i("Current pods."),
            "readyReplicas": _i("Ready pods."),
            "currentRevision": _s("Revision of current pods."),
            "updateRevision": _s("Revision being rolled to."),
        })),
    "apps/v1.DaemonSet": _kind(
        "apps/v1", "DaemonSet",
        "Runs one pod per (matching) node "
        "(pkg/controller/daemon).",
        _obj("Desired state.", {
            "selector": _ref("v1.LabelSelector"),
            "template": _ref("v1.PodTemplateSpec",
                             "Node selection comes from the "
                             "template's affinity/tolerations."),
            "updateStrategy": _obj("RollingUpdate or OnDelete."),
        }),
        _obj("Observed state.", {
            "desiredNumberScheduled": _i("Nodes that should run the "
                                         "daemon pod."),
            "currentNumberScheduled": _i("Nodes running it."),
            "numberReady": _i("Nodes with a ready daemon pod."),
            "numberMisscheduled": _i("Nodes running it that should "
                                     "not."),
        })),
    "batch/v1.Job": _kind(
        "batch/v1", "Job",
        "Runs pods to completion; tracks successes "
        "(pkg/controller/job).",
        _obj("Desired state.", {
            "completions": _i("Successful pods required; default 1."),
            "parallelism": _i("Max pods running at once."),
            "backoffLimit": _i("Retries before marking failed; "
                               "default 6."),
            "activeDeadlineSeconds": _i("Wall-clock bound for the "
                                        "whole job."),
            "selector": _ref("v1.LabelSelector"),
            "template": _ref("v1.PodTemplateSpec"),
            "completionMode": _s("NonIndexed (default) or Indexed."),
            "suspend": _b("No pods are created while true."),
        }),
        _obj("Observed state.", {
            "active": _i("Running pods."),
            "succeeded": _i("Pods that completed successfully."),
            "failed": _i("Pods that failed."),
            "conditions": _arr(_obj("Complete / Failed condition."),
                               "Terminal state conditions."),
            "startTime": _s("When the controller started the job."),
            "completionTime": _s("When the job completed."),
        })),
    "autoscaling/v2.HorizontalPodAutoscaler": _kind(
        "autoscaling/v2", "HorizontalPodAutoscaler",
        "Scales a workload's replica count to hold a metric target "
        "(pkg/controller/podautoscaler).",
        _obj("Autoscaler spec.", {
            "scaleTargetRef": _obj("Workload to scale.", {
                "apiVersion": _s("Target apiVersion."),
                "kind": _s("Target kind."),
                "name": _s("Target name."),
            }),
            "minReplicas": _i("Lower bound; default 1."),
            "maxReplicas": _i("Upper bound."),
            "metrics": _arr(_obj("Metric source (Resource/Pods/"
                                 "Object/External)."),
                            "Targets driving the scale decision."),
        }),
        _obj("Observed state.", {
            "currentReplicas": _i("Current count."),
            "desiredReplicas": _i("Last computed target."),
            "conditions": _arr(_obj("ScalingActive / AbleToScale "
                                    "condition."),
                               "Autoscaler conditions."),
        })),
}


def install(definitions: dict[str, dict]) -> None:
    """Overlay the structural schemas onto an openapi_v2 definitions
    map: shared sub-definitions first, then top-level kinds (replacing
    skeletons of the same key)."""
    for key, schema in DEFINITIONS.items():
        definitions.setdefault(key, schema)
    for key, schema in KIND_SCHEMAS.items():
        definitions[key] = schema
