"""Patch application: JSON merge patch, JSON patch, strategic merge patch.

Reference: the three patch content types the kube-apiserver accepts
(staging/src/k8s.io/apiserver/pkg/endpoints/handlers/patch.go):
  application/merge-patch+json           RFC 7386 (vendored evanphx/json-patch)
  application/json-patch+json            RFC 6902 op list
  application/strategic-merge-patch+json apimachinery/pkg/util/strategicpatch

Strategic merge is the Kubernetes-specific one: lists tagged
patchStrategy=merge in the API types merge element-wise by a patch *merge
key* instead of being replaced wholesale.  The merge-key table below covers
the core types (containers by name, tolerations-by-key is actually atomic
upstream — kept replace — env by name, ports by containerPort, volumes by
name, ...), plus the $patch: delete/replace directives.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List

# path-suffix -> merge key for strategic list merges (from the
# +patchMergeKey tags in staging/src/k8s.io/api/core/v1/types.go)
STRATEGIC_MERGE_KEYS: Dict[str, str] = {
    "containers": "name",
    "initContainers": "name",
    "ephemeralContainers": "name",
    "volumes": "name",
    "env": "name",
    "ports": "containerPort",
    "volumeMounts": "mountPath",
    "imagePullSecrets": "name",
    "hostAliases": "ip",
    "conditions": "type",
    "taints": "key",
    "addresses": "type",
    "finalizers": None,  # set-style (patchStrategy=merge, scalar)
}


class PatchError(ValueError):
    pass


# -- RFC 7386 JSON merge patch --------------------------------------------

def json_merge_patch(target: Any, patch: Any) -> Any:
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    if not isinstance(target, dict):
        target = {}
    result = dict(target)
    for k, v in patch.items():
        if v is None:
            result.pop(k, None)
        else:
            result[k] = json_merge_patch(result.get(k), v)
    return result


# -- RFC 6902 JSON patch ---------------------------------------------------

def _ptr_tokens(pointer: str) -> List[str]:
    if pointer == "":
        return []
    if not pointer.startswith("/"):
        raise PatchError("invalid JSON pointer %r" % pointer)
    return [t.replace("~1", "/").replace("~0", "~")
            for t in pointer[1:].split("/")]


def _ptr_walk(doc: Any, tokens: List[str]):
    """-> (parent, last_token); resolves all but the last token."""
    cur = doc
    for t in tokens[:-1]:
        if isinstance(cur, list):
            cur = cur[int(t)]
        elif isinstance(cur, dict):
            if t not in cur:
                raise PatchError("path not found: %r" % t)
            cur = cur[t]
        else:
            raise PatchError("cannot traverse %r" % t)
    return cur, (tokens[-1] if tokens else None)


def json_patch(target: Any, ops: List[dict]) -> Any:
    doc = copy.deepcopy(target)
    for op in ops:
        try:
            doc = _apply_op(doc, op)
        except PatchError:
            raise
        except (ValueError, IndexError, KeyError, TypeError) as e:
            raise PatchError("invalid patch op %s: %s" % (op, e))
    return doc


def _apply_op(doc: Any, op: dict) -> Any:
    kind = op.get("op")
    tokens = _ptr_tokens(op.get("path", ""))
    value = op.get("value")
    if not tokens:  # whole-document ops
        if kind in ("replace", "add"):
            return copy.deepcopy(value)
        if kind == "test":
            if doc != value:
                raise PatchError("test failed at root")
            return doc
        raise PatchError("unsupported root op %r" % kind)
    parent, last = _ptr_walk(doc, tokens)
    if kind == "add":
        if isinstance(parent, list):
            idx = len(parent) if last == "-" else int(last)
            parent.insert(idx, copy.deepcopy(value))
        else:
            parent[last] = copy.deepcopy(value)
    elif kind == "replace":
        if isinstance(parent, list):
            parent[int(last)] = copy.deepcopy(value)
        else:
            if last not in parent:
                raise PatchError("replace of missing key %r" % last)
            parent[last] = copy.deepcopy(value)
    elif kind == "remove":
        if isinstance(parent, list):
            del parent[int(last)]
        else:
            if last not in parent:
                raise PatchError("remove of missing key %r" % last)
            del parent[last]
    elif kind == "test":
        cur = parent[int(last)] if isinstance(parent, list) else parent.get(last)
        if cur != value:
            raise PatchError("test failed at %s" % op.get("path"))
    elif kind in ("move", "copy"):
        src = _ptr_tokens(op.get("from", ""))
        sparent, slast = _ptr_walk(doc, src)
        val = (sparent[int(slast)] if isinstance(sparent, list)
               else sparent[slast])
        if kind == "move":
            if isinstance(sparent, list):
                del sparent[int(slast)]
            else:
                del sparent[slast]
        if isinstance(parent, list):
            idx = len(parent) if last == "-" else int(last)
            parent.insert(idx, copy.deepcopy(val))
        else:
            parent[last] = copy.deepcopy(val)
    else:
        raise PatchError("unknown op %r" % kind)
    return doc


# -- strategic merge patch -------------------------------------------------

def strategic_merge_patch(target: Any, patch: Any, field: str = "") -> Any:
    if isinstance(patch, dict):
        if patch.get("$patch") == "replace":
            out = {k: copy.deepcopy(v) for k, v in patch.items()
                   if k != "$patch"}
            return out
        if not isinstance(target, dict):
            target = {}
        result = dict(target)
        for k, v in patch.items():
            if k == "$patch":
                continue
            if v is None:
                result.pop(k, None)
            else:
                result[k] = strategic_merge_patch(result.get(k), v, k)
        return result
    if isinstance(patch, list):
        merge_key = STRATEGIC_MERGE_KEYS.get(field, "__absent__")
        if merge_key == "__absent__":
            return copy.deepcopy(patch)  # atomic list: replace
        if merge_key is None:
            # set-style scalar list: union, patch order last
            base = [x for x in (target or []) if x not in patch]
            return base + copy.deepcopy(patch)
        return _merge_list_by_key(target or [], patch, merge_key)
    return copy.deepcopy(patch)


def _merge_list_by_key(target: List[dict], patch: List[dict],
                       key: str) -> List[dict]:
    out = [copy.deepcopy(x) for x in target]
    index = {x.get(key): i for i, x in enumerate(out)
             if isinstance(x, dict)}
    for p in patch:
        if not isinstance(p, dict):
            out.append(copy.deepcopy(p))
            continue
        k = p.get(key)
        if p.get("$patch") == "delete":
            if k in index:
                out = [x for x in out
                       if not (isinstance(x, dict) and x.get(key) == k)]
                index = {x.get(key): i for i, x in enumerate(out)
                         if isinstance(x, dict)}
            continue
        if k in index:
            out[index[k]] = strategic_merge_patch(out[index[k]], p)
        else:
            out.append(copy.deepcopy(p))
            index[k] = len(out) - 1
    return out


CONTENT_TYPE_HANDLERS = {
    "application/merge-patch+json": json_merge_patch,
    "application/json-patch+json": json_patch,
    "application/strategic-merge-patch+json": strategic_merge_patch,
}


def apply_patch(content_type: str, target: Any, patch: Any) -> Any:
    fn = CONTENT_TYPE_HANDLERS.get(content_type.split(";")[0].strip())
    if fn is None:
        raise PatchError("unsupported patch content type %r" % content_type)
    return fn(target, patch)
