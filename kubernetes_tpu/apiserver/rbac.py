"""RBAC authorization — the authorizer stage of the handler chain.

Reference semantics:
  staging/src/k8s.io/apiserver/pkg/server/config.go:815 — authorization
    runs on every request, after authn/APF, before routing;
  plugin/pkg/auth/authorizer/rbac/rbac.go — RBACAuthorizer walks
    ClusterRoleBindings (cluster-wide grants) and RoleBindings (namespace
    grants), resolving each roleRef to its rule list;
  pkg/registry/rbac/validation/rule.go — rule matching: verbs,
    apiGroups, resources ("pods/status" form for subresources, "*"
    wildcards), resourceNames;
  plugin/pkg/auth/authorizer/rbac/bootstrappolicy/ — the default
    cluster roles every control-plane component is born with.

Design: policy objects are ordinary resources in the store (roles /
rolebindings namespaced; clusterroles / clusterrolebindings
cluster-scoped).  The authorizer compiles them into a per-subject index
and watches the four resources, recompiling lazily after a change — the
hot path is two dict lookups plus rule scans for one subject, no store
reads.  Identity comes from the authn stage as (user, [groups]).

The in-process LocalClient bypasses HTTP and therefore authorization, by
construction: the enforcement seam is the apiserver handler chain, same
as the reference (a process that holds the store object IS the apiserver
process).
"""

from __future__ import annotations

import threading

from ..api import meta
from ..store import kv

ROLES = "roles"
CLUSTERROLES = "clusterroles"
ROLEBINDINGS = "rolebindings"
CLUSTERROLEBINDINGS = "clusterrolebindings"

RBAC_RESOURCES = (ROLES, CLUSTERROLES, ROLEBINDINGS, CLUSTERROLEBINDINGS)

SUPERUSER_GROUP = "system:masters"


class Attributes:
    """One authorization question (authorizer.Attributes)."""

    __slots__ = ("user", "groups", "verb", "resource", "subresource",
                 "namespace", "name")

    def __init__(self, user: str, groups: tuple[str, ...], verb: str,
                 resource: str, subresource: str = "",
                 namespace: str = "", name: str = ""):
        self.user = user
        self.groups = groups
        self.verb = verb
        self.resource = resource
        self.subresource = subresource
        self.namespace = namespace
        self.name = name


def _rule_matches(rule: dict, attrs: Attributes) -> bool:
    verbs = rule.get("verbs") or []
    if "*" not in verbs and attrs.verb not in verbs:
        return False
    resources = rule.get("resources") or []
    target = attrs.resource
    if attrs.subresource:
        target = f"{attrs.resource}/{attrs.subresource}"
    ok = False
    for r in resources:
        if r == "*" or r == target:
            ok = True
            break
        # "*/status" matches any resource's status subresource
        if attrs.subresource and r == f"*/{attrs.subresource}":
            ok = True
            break
    if not ok:
        return False
    names = rule.get("resourceNames") or []
    if names and attrs.name not in names:
        return False
    return True


class RBACAuthorizer:
    """Compiles bindings into {subject: grants} and answers authorize().

    Subjects are "User:<name>" / "Group:<name>" strings.  Grants are
    (namespace_or_None, rules) pairs: None namespace = cluster-wide.
    """

    def __init__(self, store: kv.MemoryStore):
        self._store = store
        self._lock = threading.Lock()
        self._index: dict[str, list[tuple[str | None, list[dict]]]] = {}
        self._dirty = True
        self._watches = []
        for res in RBAC_RESOURCES:
            w = store.watch(res)
            self._watches.append(w)
            t = threading.Thread(target=self._watch_loop, args=(w,),
                                 name=f"rbac-watch-{res}", daemon=True)
            t.start()

    def stop(self) -> None:
        for w in self._watches:
            w.stop()

    def _watch_loop(self, w: kv.Watch) -> None:
        while True:
            evs = w.next_batch(timeout=None)
            if not evs and w.stopped:
                return
            if evs:
                with self._lock:
                    self._dirty = True

    # -- compilation -----------------------------------------------------

    def _role_rules(self, kind: str, name: str, namespace: str) -> list[dict]:
        try:
            if kind == "ClusterRole":
                obj = self._store.get(CLUSTERROLES, "", name)
            else:
                obj = self._store.get(ROLES, namespace, name)
        except kv.NotFoundError:
            return []  # dangling roleRef grants nothing (reference behavior)
        return obj.get("rules") or []

    def _recompile(self) -> None:
        index: dict[str, list[tuple[str | None, list[dict]]]] = {}

        def add(subjects, scope_ns, rules):
            if not rules:
                return
            for s in subjects or []:
                skey = f"{s.get('kind', 'User')}:{s.get('name', '')}"
                index.setdefault(skey, []).append((scope_ns, rules))

        crbs, _ = self._store.list(CLUSTERROLEBINDINGS)
        for b in crbs:
            ref = b.get("roleRef") or {}
            rules = self._role_rules("ClusterRole", ref.get("name", ""), "")
            add(b.get("subjects"), None, rules)
        rbs, _ = self._store.list(ROLEBINDINGS)
        for b in rbs:
            ns = meta.namespace(b)
            ref = b.get("roleRef") or {}
            # a RoleBinding may reference a ClusterRole but only grants it
            # INSIDE its own namespace (rbac.go AppliesTo)
            rules = self._role_rules(ref.get("kind", "Role"),
                                     ref.get("name", ""), ns)
            add(b.get("subjects"), ns, rules)
        self._index = index

    # -- the authorizer stage --------------------------------------------

    def authorize(self, attrs: Attributes) -> bool:
        if SUPERUSER_GROUP in attrs.groups:
            return True  # the privileged-group authorizer ahead of RBAC
        with self._lock:
            if self._dirty:
                self._recompile()
                self._dirty = False
            index = self._index
        subjects = [f"User:{attrs.user}"]
        subjects += [f"Group:{g}" for g in attrs.groups]
        for skey in subjects:
            for scope_ns, rules in index.get(skey, ()):
                if scope_ns is not None and scope_ns != attrs.namespace:
                    continue
                for rule in rules:
                    if _rule_matches(rule, attrs):
                        return True
        return False


NODE_USER_PREFIX = "system:node:"
NODES_GROUP = "system:nodes"

# what a kubelet may read broadly (informers watch cluster-wide; field-
# selector-scoped watches are a non-goal here)
_NODE_READABLE = frozenset({
    "pods", "nodes", "services", "endpointslices", "configmaps",
    "persistentvolumeclaims", "persistentvolumes", "leases", "podgroups",
})


class NodeAuthorizer:
    """Scope a kubelet credential to ITS OWN node's objects.

    Reference: plugin/pkg/auth/authorizer/node/ — the node authorizer
    walks a graph from the node to the objects its pods reference, and
    the NodeRestriction admission plugin pins writes to the node's own
    identity.  Reduced here to the load-bearing rules:

      - writes to nodes/leases only for the node's OWN name
      - pod writes (status reports) only for pods BOUND to this node
      - secret gets only when a pod on this node references the secret
        (volumes or env); secret list/watch denied
      - broad reads for the informer-watched resources
      - event creation allowed (kubelets report)

    Handles ONLY system:node:* users in system:nodes; everything else
    falls through (False) to the next authorizer in the union."""

    def __init__(self, store: kv.MemoryStore):
        self._store = store

    def _pod_on_node(self, namespace: str, name: str, node: str) -> bool:
        try:
            pod = self._store.get("pods", namespace, name)
        except kv.NotFoundError:
            return False
        return (pod.get("spec") or {}).get("nodeName") == node

    def _secret_referenced(self, namespace: str, name: str,
                           node: str) -> bool:
        """graph.go lite: is `name` referenced by any pod on `node`?"""
        try:
            pods, _ = self._store.list("pods", namespace)
        except kv.StoreError:
            return False
        for pod in pods:
            spec = pod.get("spec") or {}
            if spec.get("nodeName") != node:
                continue
            for ref in spec.get("imagePullSecrets") or ():
                if ref.get("name") == name:
                    return True
            for vol in spec.get("volumes") or ():
                if ((vol.get("secret") or {}).get("secretName")) == name:
                    return True
                for src in ((vol.get("projected") or {})
                            .get("sources")) or ():
                    if ((src.get("secret") or {}).get("name")) == name:
                        return True
            containers = list(spec.get("containers") or ())
            containers += list(spec.get("initContainers") or ())
            for c in containers:
                for env in c.get("env") or ():
                    ref = ((env.get("valueFrom") or {})
                           .get("secretKeyRef") or {})
                    if ref.get("name") == name:
                        return True
                for src in c.get("envFrom") or ():
                    if ((src.get("secretRef") or {}).get("name")) == name:
                        return True
        return False

    def authorize(self, attrs: Attributes) -> bool:
        if not attrs.user.startswith(NODE_USER_PREFIX) \
                or NODES_GROUP not in attrs.groups:
            return False
        node = attrs.user[len(NODE_USER_PREFIX):]
        verb, res = attrs.verb, attrs.resource
        if verb in ("get", "list", "watch"):
            if res in _NODE_READABLE:
                return True
            if res == "secrets" and verb == "get":
                return self._secret_referenced(attrs.namespace,
                                               attrs.name, node)
            return False
        if res == "events":
            return verb == "create"
        if res == "nodes":
            # update/patch/delete pinned to own name; create has no
            # name at authz time (NodeRestriction admission would pin
            # it) — allow, registration is the join flow
            return verb == "create" or attrs.name == node
        if res == "leases":
            # node heartbeat leases live ONLY in kube-node-lease
            # (upstream pins the namespace the same way) — a kubelet
            # cert must not forge identity leases elsewhere
            if attrs.namespace != "kube-node-lease":
                return False
            return verb == "create" or attrs.name == node
        if res == "pods":
            if verb in ("update", "patch"):
                return self._pod_on_node(attrs.namespace, attrs.name,
                                         node)
            return False
        if res == "certificatesigningrequests":
            return verb == "create"
        return False


class CompositeAuthorizer:
    """Union of authorization modes (--authorization-mode=Node,RBAC):
    any module granting wins; all abstaining/denying denies."""

    def __init__(self, authorizers: list):
        self.authorizers = authorizers

    def authorize(self, attrs: Attributes) -> bool:
        return any(a.authorize(attrs) for a in self.authorizers)

    def stop(self) -> None:
        for a in self.authorizers:
            stop = getattr(a, "stop", None)
            if stop is not None:
                stop()


# -- bootstrap policy ----------------------------------------------------

def _role(name: str, rules: list[dict]) -> dict:
    obj = meta.new_object("ClusterRole", name, None)
    obj["rules"] = rules
    return obj


def _binding(name: str, role: str, subjects: list[dict]) -> dict:
    obj = meta.new_object("ClusterRoleBinding", name, None)
    obj["roleRef"] = {"kind": "ClusterRole", "name": role}
    obj["subjects"] = subjects
    return obj


def _user(name: str) -> dict:
    return {"kind": "User", "name": name}


def _group(name: str) -> dict:
    return {"kind": "Group", "name": name}


READ = ["get", "list", "watch"]
WRITE = ["create", "update", "patch", "delete"]


def bootstrap_policy(store: kv.MemoryStore) -> None:
    """Default roles + bindings for the control-plane components
    (bootstrappolicy/policy.go ClusterRoles()/ClusterRoleBindings(),
    reduced to the verbs this control plane actually issues).
    Idempotent — crash-only restart safe."""
    roles = [
        _role("cluster-admin",
              [{"verbs": ["*"], "apiGroups": ["*"], "resources": ["*"]}]),
        _role("system:kube-scheduler", [
            {"verbs": READ, "resources": [
                "pods", "nodes", "namespaces", "services", "replicasets",
                "statefulsets", "replicationcontrollers",
                "poddisruptionbudgets", "persistentvolumeclaims",
                "persistentvolumes", "storageclasses", "csinodes",
                "podgroups", "priorityclasses"]},
            {"verbs": ["create"], "resources": ["pods/binding", "bindings"]},
            {"verbs": ["update", "patch"], "resources": ["pods/status"]},
            {"verbs": ["delete"], "resources": ["pods"]},  # preemption
            {"verbs": ["create", "patch", "update"], "resources": ["events"]},
            {"verbs": ["get", "create", "update"], "resources": ["leases"]},
        ]),
        _role("system:kube-controller-manager", [
            {"verbs": READ, "resources": ["*"]},
            {"verbs": WRITE, "resources": [
                "pods", "replicasets", "services", "endpoints",
                "endpointslices", "serviceaccounts", "secrets", "configmaps",
                "leases", "events", "namespaces", "podgroups",
                "persistentvolumes", "persistentvolumeclaims",
                "volumeattachments", "certificatesigningrequests",
                "poddisruptionbudgets", "horizontalpodautoscalers"]},
            {"verbs": ["update", "patch"], "resources": [
                "*/status", "*/scale", "nodes", "deployments", "jobs",
                "cronjobs", "statefulsets", "daemonsets",
                "replicationcontrollers", "certificatesigningrequests/status",
                "certificatesigningrequests/approval"]},
            {"verbs": ["delete"], "resources": ["nodes"]},  # node lifecycle
        ]),
        _role("system:node", [
            {"verbs": READ, "resources": [
                "pods", "nodes", "services", "configmaps", "secrets",
                "persistentvolumeclaims", "persistentvolumes"]},
            {"verbs": ["create", "update", "patch"], "resources": [
                "nodes", "nodes/status", "pods/status", "events", "leases"]},
            {"verbs": ["create"], "resources": [
                "certificatesigningrequests"]},
            {"verbs": ["delete"], "resources": ["pods"]},  # eviction/own-pod
        ]),
        _role("system:kube-proxy", [
            {"verbs": READ, "resources": [
                "services", "endpoints", "endpointslices", "nodes"]},
            {"verbs": ["create", "patch", "update"], "resources": ["events"]},
        ]),
        _role("system:basic-user", [
            # any authenticated user may ask "can I?" (SelfSubjectAccessReview)
            {"verbs": ["create"],
             "resources": ["selfsubjectaccessreviews"]},
        ]),
        _role("system:node-bootstrapper", [
            # a joining node's bootstrap-token identity may submit CSRs,
            # watch for the issued certificate, and replace a stale CSR
            # left by an earlier failed join
            {"verbs": ["create", "get", "list", "watch", "delete"],
             "resources": ["certificatesigningrequests"]},
        ]),
        # user-facing roles (aggregationRule reduced to static rules)
        _role("admin", [
            {"verbs": ["*"], "resources": ["*"]}]),
        _role("edit", [
            {"verbs": READ + WRITE, "resources": [
                "pods", "deployments", "replicasets", "statefulsets",
                "daemonsets", "jobs", "cronjobs", "services", "endpoints",
                "configmaps", "secrets", "persistentvolumeclaims",
                "horizontalpodautoscalers", "poddisruptionbudgets"]}]),
        _role("view", [
            {"verbs": READ, "resources": [
                "pods", "deployments", "replicasets", "statefulsets",
                "daemonsets", "jobs", "cronjobs", "services", "endpoints",
                "configmaps", "persistentvolumeclaims",
                "horizontalpodautoscalers", "poddisruptionbudgets"]}]),
    ]
    bindings = [
        _binding("cluster-admin", "cluster-admin",
                 [_group(SUPERUSER_GROUP)]),
        _binding("system:kube-scheduler", "system:kube-scheduler",
                 [_user("system:kube-scheduler")]),
        _binding("system:kube-controller-manager",
                 "system:kube-controller-manager",
                 [_user("system:kube-controller-manager")]),
        _binding("system:node", "system:node",
                 # cert-authenticated kubelets (system:nodes via the
                 # cert's O field) are scoped by the NodeAuthorizer,
                 # not this broad role; plain-HTTP clusters have no
                 # cert authn, so the bootstrap-token identity keeps
                 # node rights here
                 [_group("system:bootstrappers")]),
        _binding("system:node-bootstrapper", "system:node-bootstrapper",
                 [_group("system:bootstrappers")]),
        _binding("system:basic-user", "system:basic-user",
                 [_group("system:authenticated")]),
        _binding("system:kube-proxy", "system:kube-proxy",
                 [_user("system:kube-proxy")]),
    ]
    for obj in roles:
        try:
            store.create(CLUSTERROLES, obj)
        except kv.AlreadyExistsError:
            pass
    for obj in bindings:
        try:
            store.create(CLUSTERROLEBINDINGS, obj)
        except kv.AlreadyExistsError:
            pass
    # kube-public/cluster-info is readable ANONYMOUSLY — the kubeadm join
    # trust bootstrap depends on it (bootstrappolicy: the
    # kubeadm:bootstrap-signer-clusterinfo Role + binding in kube-public)
    info_role = meta.new_object("Role", "kubeadm:bootstrap-signer-clusterinfo",
                                "kube-public")
    info_role["rules"] = [{"verbs": ["get"], "resources": ["configmaps"],
                           "resourceNames": ["cluster-info"]}]
    info_rb = meta.new_object("RoleBinding",
                              "kubeadm:bootstrap-signer-clusterinfo",
                              "kube-public")
    info_rb["roleRef"] = {"kind": "Role",
                          "name": "kubeadm:bootstrap-signer-clusterinfo"}
    info_rb["subjects"] = [_user("system:anonymous"),
                           _group("system:unauthenticated")]
    for res, obj in ((ROLES, info_role), (ROLEBINDINGS, info_rb)):
        try:
            store.create(res, obj)
        except kv.AlreadyExistsError:
            pass
