"""REST+watch API server over the store.

Reference: the kube-apiserver serving stack, reduced to its load-bearing
contract (SURVEY.md layers 4-5):
  staging/src/k8s.io/apiserver/pkg/endpoints/installer.go:190 (routes)
    GET    /api/v1/{resource}                       list (all namespaces)
    GET    /api/v1/namespaces/{ns}/{resource}       list
    GET    /api/v1/namespaces/{ns}/{resource}/{nm}  get
    POST   /api/v1/namespaces/{ns}/{resource}       create
    PUT    /api/v1/namespaces/{ns}/{resource}/{nm}  update (CAS -> 409)
    PATCH  ...                                      merge/json/strategic patch
    DELETE /api/v1/namespaces/{ns}/{resource}/{nm}  delete
    GET    ...?watch=true&resourceVersion=N         newline-delimited JSON
                                                    event stream
  /apis/{group}/{version}/... serves the same verbs for grouped + custom
  resources (apiextensions-apiserver shape); subresources:
    PUT/PATCH .../{name}/status      status-only writes (registry strategies)
    POST      .../pods/{name}/binding    writes spec.nodeName (scheduler)
    POST      .../pods/{name}/eviction   PDB-checked delete (429 if blocked)
    GET/PUT   .../{name}/scale           replica count subresource

Handler chain (DefaultBuildHandlerChain, server/config.go:813, in order):
  request log -> authn (bearer token) -> audit -> API priority & fairness
  -> authorization (RBAC, rbac.py)
  -> route -> admission chain (mutating then validating) -> registry/store.

Errors are metav1.Status-shaped JSON with the right HTTP codes
(404/409/410 Gone for compacted watches/422 validation/429 APF).
Cluster-scoped resources (nodes, ...) use an empty namespace key; the routes
also accept /api/v1/{resource}/{name} for them.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlencode, urlparse

from .. import __version__
from ..api import core_versions as corever
from ..api import meta
from ..component_base import configz
from ..store import kv
from . import admission as adm
from . import audit as auditlib
from . import authn as authnlib
from . import crd as crdlib
from . import flowcontrol
from . import managedfields as mflib
from . import patch as patchlib
from . import rbac as rbaclib

logger = logging.getLogger(__name__)

from ..client.clientset import CLUSTER_SCOPED_RESOURCES

# alias, not a copy (a fork would re-split client/server scoping); the
# server enforces it on create: namespaced paths for these 400, and any
# client-supplied metadata.namespace is stripped so storage keys match
# the cluster-scoped read paths
CLUSTER_SCOPED = CLUSTER_SCOPED_RESOURCES

SUBRESOURCES = {"status", "binding", "eviction", "scale"}

# pod-only subresources served by tunneling to the pod's kubelet
# (pkg/registry/core/pod/rest/subresources.go -> UpgradeAwareProxy);
# routed only for pods and only on GET/POST — never as write targets
NODE_STREAM_SUBRESOURCES = {"log", "exec", "attach", "portforward"}

# subresources with no stored object behind them: tunnels + token minting
# (serviceaccounts/{name}/token is POST-only, token.go) — a write verb
# must never fall through to the parent object
VIRTUAL_SUBRESOURCES = NODE_STREAM_SUBRESOURCES | {"token"}

# built-in group routing (/apis/{group}/{version}); all resources share the
# flat store namespace, so the group prefix is addressing only
BUILTIN_GROUPS = {
    "apps": {"deployments", "replicasets", "statefulsets", "daemonsets"},
    "batch": {"jobs", "cronjobs"},
    "policy": {"poddisruptionbudgets"},
    "scheduling.k8s.io": {"priorityclasses"},
    "storage.k8s.io": {"storageclasses", "csinodes", "volumeattachments"},
    "coordination.k8s.io": {"leases"},
    "apiextensions.k8s.io": {crdlib.CRDS},
    "autoscaling": {"horizontalpodautoscalers"},
    "certificates.k8s.io": {"certificatesigningrequests"},
    "discovery.k8s.io": {"endpointslices"},
    "apiregistration.k8s.io": {"apiservices"},
    "flowcontrol.apiserver.k8s.io": {"flowschemas",
                                     "prioritylevelconfigurations"},
}

SCALABLE = {"deployments", "replicasets", "statefulsets",
            "replicationcontrollers"}


class AdmissionError(Exception):
    pass


def status_error(code: int, reason: str, message: str) -> dict:
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": message, "code": code}


def bind_conflict_status(err) -> dict:
    """409 Status for kv.BindConflict with the structured fields in
    `details`, so an HTTP scheduler rehydrates the same typed error a
    LocalClient one sees (the already_bound_same_node classification
    needs current_node, not message parsing)."""
    status = status_error(409, "BindConflict", str(err))
    status["details"] = {"name": err.key,
                         "currentNode": err.current_node,
                         "wantedNode": err.wanted_node}
    return status


class _QuietTLSServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that doesn't spray tracebacks when a TLS
    handshake fails (wrong client CA, plain-HTTP probe, port scan) —
    those are client errors, not server bugs.  Genuine server faults
    (bare OSError: ENOSPC, EMFILE) still get the full report."""

    def handle_error(self, request, client_address):
        import ssl
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (ssl.SSLError, ConnectionError, TimeoutError)):
            logger.debug("connection error from %s: %s",
                         client_address, exc)
            return
        super().handle_error(request, client_address)


class _Route:
    __slots__ = ("resource", "ns", "name", "subresource", "group", "version",
                 "query", "path")

    def __init__(self, resource=None, ns=None, name=None, subresource=None,
                 group=None, version="v1", query=None, path=""):
        self.resource = resource
        self.ns = ns
        self.name = name
        self.subresource = subresource
        self.group = group
        self.version = version
        self.query = query or {}
        self.path = path


class APIServer:
    def __init__(self, store: kv.MemoryStore, host: str = "127.0.0.1",
                 port: int = 0, token: str | None = None,
                 tokens: dict[str, tuple[str, tuple[str, ...]]] | None = None,
                 enable_rbac: bool = False,
                 bootstrap_token_auth: bool = False,
                 admission_chain: adm.Chain | None = None,
                 enable_default_admission: bool = False,
                 flow_dispatcher: flowcontrol.Dispatcher | None = None,
                 audit_logger: auditlib.AuditLogger | None = None,
                 tls: dict | None = None,
                 enable_service_accounts: bool = False,
                 disable_admission_plugins: set | frozenset = frozenset()):
        self.store = store
        self.token = token
        # static bearer tokens -> identity (the reference's token-auth
        # file: one line per token,user,groups).  The legacy single
        # `token` becomes a superuser credential.
        self.tokens = dict(tokens or {})
        if token is not None:
            self.tokens.setdefault(
                token, ("system:admin", (rbaclib.SUPERUSER_GROUP,)))
        # --authorization-mode=Node,RBAC: the node authorizer scopes
        # kubelet certs to their own node's objects; RBAC covers the rest
        self.authorizer = rbaclib.CompositeAuthorizer(
            [rbaclib.NodeAuthorizer(store),
             rbaclib.RBACAuthorizer(store)]) if enable_rbac else None
        # bootstrap token authenticator (plugin/pkg/auth/authenticator/
        # token/bootstrap): live lookup of kube-system bootstrap Secrets,
        # so `kubeadm join --token` credentials work without restarting
        self.bootstrap_token_auth = bootstrap_token_auth
        self.admission_hooks: list = []  # legacy fn(verb, resource, obj) hooks

        def _authorize_for_admission(user, groups, verb, resource,
                                     subresource, ns, name) -> bool:
            """OwnerReferencesPermissionEnforcement's authorizer seam."""
            if self.authorizer is None:
                return True
            return self.authorizer.authorize(rbaclib.Attributes(
                user, tuple(groups), verb, resource, subresource, ns,
                name))

        self.admission_chain = admission_chain or (
            adm.default_chain(store, _authorize_for_admission,
                              disable=disable_admission_plugins)
            if enable_default_admission else adm.Chain())
        self.flow = flow_dispatcher  # None = APF filter disabled
        self.audit = audit_logger
        self.crds = crdlib.CRDRegistry()
        from . import aggregator as agglib
        self.aggregator = agglib.AggregatorRegistry(
            store, local_groups=set(BUILTIN_GROUPS),
            is_local=lambda group: group in self.crds.groups())
        self.metrics = {"requests_total": 0, "watch_streams": 0,
                        "requests_rejected_total": 0}
        self._metrics_lock = threading.Lock()
        # re-establish CRDs already persisted (restart = re-list, crash-only)
        try:
            existing, _ = store.list(crdlib.CRDS)
            for obj in existing:
                try:
                    self.crds.establish(obj)
                except crdlib.ValidationError:
                    logger.warning("skipping invalid persisted CRD %s",
                                   meta.name(obj))
        except Exception:  # noqa: BLE001 — store without that resource yet
            pass
        # ServiceAccount token issuer (TokenRequest + SA JWT authn —
        # pkg/serviceaccount/jwt.go); opt-in: it persists a signing-key
        # Secret in kube-system
        self.sa_issuer = (authnlib.ServiceAccountIssuer(store)
                          if enable_service_accounts else None)
        handler = self._make_handler()
        self.httpd = _QuietTLSServer((host, port), handler)
        self.httpd.daemon_threads = True
        # TLS serving + X.509 client-cert authn (x509.go): wrap the
        # listening socket; a client cert chained to client_ca_file
        # authenticates as CN/O
        self.tls = tls
        self.client_ca_auth = bool(tls and tls.get("client_ca_file"))
        if tls:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls["cert_file"],
                                keyfile=tls["key_file"])
            if self.client_ca_auth:
                ctx.load_verify_locations(cafile=tls["client_ca_file"])
                ctx.verify_mode = ssl.CERT_OPTIONAL
            # handshake deferred to the per-request handler thread
            # (Handler.setup): with do_handshake_on_connect=True a single
            # silent client would stall the accept loop — and every
            # other connection — for the duration of its handshake
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def _bootstrap_identity(self, token: str
                            ) -> tuple[str, tuple[str, ...]] | None:
        import hmac as hmaclib
        import time as timelib
        tid, _, tsec = token.partition(".")
        if not tid or not tsec:
            return None
        try:
            sec = self.store.get("secrets", "kube-system",
                                 f"bootstrap-token-{tid}")
        except kv.NotFoundError:
            return None
        if sec.get("type") != "bootstrap.kubernetes.io/token":
            return None
        data = sec.get("data") or {}
        if not hmaclib.compare_digest(str(data.get("token-secret", "")),
                                      tsec):
            return None
        if data.get("usage-bootstrap-authentication") != "true":
            return None
        exp = data.get("expiration")
        try:
            if exp is not None and float(exp) < timelib.time():
                return None
        except (TypeError, ValueError):
            return None
        return (f"system:bootstrap:{tid}", ("system:bootstrappers",))

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "APIServer":
        self.bootstrap_system()
        if self.flow is not None:
            # FlowSchema/PriorityLevelConfiguration objects drive the
            # dispatcher from here on (apf_controller.go)
            self.flow.bind_store(self.store)
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="apiserver", daemon=True)
        self._thread.start()
        return self

    def bootstrap_system(self) -> None:
        """System namespaces + the kubernetes Service — what the reference's
        controlplane bootstrap-controller materializes on startup
        (pkg/controlplane/controller.go RunKubernetesNamespaces/
        RunKubernetesService).  Idempotent; crash-only restart safe."""
        for ns in ("default", "kube-system", "kube-public",
                   "kube-node-lease"):
            obj = meta.new_object("Namespace", ns, None)
            obj["status"] = {"phase": "Active"}
            try:
                self.store.create("namespaces", obj)
            except kv.AlreadyExistsError:
                pass
        svc = meta.new_object("Service", "kubernetes", "default")
        svc["spec"] = {"type": "ClusterIP", "clusterIP": "10.96.0.1",
                       "ports": [{"name": "https", "port": 443,
                                  "protocol": "TCP",
                                  "targetPort": self.port}]}
        try:
            self.store.create("services", svc)
        except kv.AlreadyExistsError:
            pass
        if self.authorizer is not None:
            rbaclib.bootstrap_policy(self.store)

    def stop(self) -> None:
        if self.authorizer is not None:
            self.authorizer.stop()
        if self.flow is not None:
            self.flow.stop()
        self.aggregator.stop()
        self.httpd.shutdown()
        self.httpd.server_close()  # release the listening socket

    @property
    def url(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.httpd.server_address[0]}:{self.port}"

    # -- request handling ------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # response head and body go out as separate writes; with
            # Nagle on, the body waits for the client's delayed ACK —
            # a measured 40ms stall PER REQUEST on loopback (23 ->
            # 2700 req/s when disabled)
            disable_nagle_algorithm = True

            def setup(self):
                # deferred TLS handshake (see the wrap_socket call):
                # bounded so a silent peer costs one handler thread for
                # 30s, not the accept loop.  self.connection doesn't
                # exist until super().setup(); the raw socket is
                # self.request here.
                if hasattr(self.request, "do_handshake"):
                    self.request.settimeout(30.0)
                    self.request.do_handshake()
                    self.request.settimeout(None)
                super().setup()

            def log_message(self, fmt, *args):  # route through logging
                logger.debug("apiserver: " + fmt, *args)

            def _send_json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _identity(self) -> tuple[str, tuple[str, ...]] | None:
                """Resolve the request's (user, groups); None = bad creds.

                A PRESENT-but-unknown bearer token is a 401.  A request
                with NO credentials authenticates as the anonymous user
                ONLY when an authorizer is configured to judge it
                (--anonymous-auth + RBAC — this is what lets `kubeadm
                join` fetch kube-public/cluster-info before it has any
                credential); with token-auth but no authorizer, anonymous
                would mean unrestricted, so it stays a 401."""
                # X.509 client cert (request/x509/x509.go): the TLS
                # layer already verified the chain against the client
                # CA; CN/O become the identity
                if server.client_ca_auth:
                    try:
                        ident = authnlib.x509_identity(
                            self.connection.getpeercert())
                    except (ValueError, OSError):
                        ident = None
                    if ident is not None:
                        user, groups = ident
                        return (user, tuple(groups)
                                + ("system:authenticated",))
                auth = self.headers.get("Authorization", "")
                authn_on = (bool(server.tokens)
                            or server.bootstrap_token_auth
                            or server.client_ca_auth
                            or server.sa_issuer is not None)
                if not authn_on or (not auth
                                    and server.authorizer is not None):
                    return ("system:anonymous", ("system:unauthenticated",))
                if auth.startswith("Bearer "):
                    bearer = auth[len("Bearer "):]
                    ident = server.tokens.get(bearer)
                    if ident is None and server.bootstrap_token_auth \
                            and "." in bearer:
                        ident = server._bootstrap_identity(bearer)
                    if ident is None and server.sa_issuer is not None \
                            and bearer.count(".") == 2:
                        ident = server.sa_issuer.verify(bearer)
                    if ident is not None:
                        # every real credential is in system:authenticated
                        # (the group system:basic-user rights bind to)
                        user, groups = ident
                        if "system:authenticated" not in groups:
                            groups = tuple(groups) + (
                                "system:authenticated",)
                        return (user, groups)
                return None

            def _user(self) -> str:
                ident = self._identity()
                return ident[0] if ident else "system:anonymous"

            def _drain_body(self) -> None:
                """Consume an unread request body before an early error
                response — leftover bytes would be parsed as the next
                request on this keep-alive connection."""
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    self.rfile.read(length)

            def _authn(self) -> bool:
                if self._identity() is not None:
                    return True
                self._drain_body()
                self._send_json(401, status_error(401, "Unauthorized",
                                                  "invalid bearer token"))
                return False

            def _maybe_proxy(self) -> bool:
                """Aggregation layer (kube-aggregator handler_proxy.go):
                requests for an /apis/<group>/<version> registered to an
                external APIService are proxied (STREAMED — watch relays
                work) to its backend.  Runs after authn/APF, and records
                the same ResponseComplete audit event local requests get."""
                from . import aggregator as agglib
                u = urlparse(self.path)
                route = server.aggregator.resolve(u.path)
                if route is None:
                    return False
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else None
                status, hdrs, resp = server.aggregator.proxy_open(
                    route[0], route[1], self.command, u.path, u.query, body,
                    dict(self.headers))
                length_hdr = next((v for k, v in hdrs.items()
                                   if k.lower() == "content-length"), None)
                has_len = length_hdr is not None
                self.send_response(status)
                for k, v in hdrs.items():
                    if k.lower() not in agglib.HOP_HEADERS:
                        self.send_header(k, v)
                if has_len:
                    # body is relayed verbatim, so the backend's length
                    # stays valid (HOP_HEADERS drops it for the loop above)
                    self.send_header("Content-Length", length_hdr)
                else:
                    # unknown length (streaming backend): relay until EOF
                    # and close — the HTTP/1.0-style framing watch clients
                    # handle fine
                    self.send_header("Connection", "close")
                self.end_headers()
                try:
                    with resp:
                        while True:
                            chunk = resp.read(65536)
                            if not chunk:
                                break
                            self.wfile.write(chunk)
                            self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client or backend went away mid-stream
                finally:
                    if not has_len:
                        self.close_connection = True
                self._audit(self._route(), self.command.lower(), status)
                return True

            def _route(self) -> _Route | None:
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                q = parse_qs(u.query)
                r = _Route(query=q, path=u.path)
                if not parts:
                    return r
                if parts[0] == "api":
                    # core ("legacy") group: /api/{version}/... — v1 is
                    # the hub/storage form; additional served versions go
                    # through the core conversion seam (api/core_versions)
                    if len(parts) >= 2:
                        r.version = parts[1]
                        if r.version not in corever.SERVED_VERSIONS:
                            return r  # unknown core version -> 404
                    rest = parts[2:]
                elif parts[0] == "apis" and len(parts) >= 3:
                    r.group, r.version = parts[1], parts[2]
                    rest = parts[3:]
                else:
                    return r
                if len(rest) >= 3 and rest[0] == "namespaces":
                    r.ns, r.resource = rest[1], rest[2]
                    if len(rest) > 3:
                        r.name = rest[3]
                    if len(rest) > 4:
                        known = SUBRESOURCES
                        if r.resource == "pods":
                            known = known | NODE_STREAM_SUBRESOURCES
                        elif r.resource == "serviceaccounts":
                            known = known | {"token"}
                        if rest[4] in known and len(rest) == 5:
                            r.subresource = rest[4]
                        else:  # unknown subresource -> 404
                            r.resource = None
                elif rest:
                    r.resource = rest[0]
                    if len(rest) > 1:
                        r.name = rest[1]
                    if len(rest) > 2:
                        if rest[2] in SUBRESOURCES and len(rest) == 3:
                            r.subresource = rest[2]
                        else:
                            r.resource = None
                if (parts[0] == "api" and r.resource
                        and r.version not in (None, corever.HUB)
                        and not corever.handles(r.resource, r.version)):
                    r.resource = None  # resource not served at this version
                return r

            # ---- shared filters ----

            def _begin(self, verb: str):
                """authn + APF admission. Returns (route, ticket) or None
                after writing the error response."""
                with server._metrics_lock:
                    server.metrics["requests_total"] += 1
                if not self._authn():
                    return None
                r = self._route()
                ticket = None
                # long-running requests (watches, kubelet streams) are
                # exempt from APF — a held seat for a stream's lifetime
                # would starve the level (upstream longRunningRequestCheck
                # exempts watch + exec/attach/portforward/log the same way)
                is_watch = bool(r) and r.query.get("watch",
                                                   ["false"])[0] == "true"
                is_long = is_watch or (
                    bool(r) and r.subresource in NODE_STREAM_SUBRESOURCES)
                if server.flow is not None and r and r.resource \
                        and not is_long:
                    ident = self._identity() or ("system:anonymous", ())
                    try:
                        ticket = server.flow.admit(ident[0], verb,
                                                   r.resource,
                                                   tuple(ident[1]))
                    except flowcontrol.RejectedError as e:
                        with server._metrics_lock:
                            server.metrics["requests_rejected_total"] += 1
                        self._drain_body()
                        body = json.dumps(status_error(
                            429, "TooManyRequests", str(e))).encode()
                        self.send_response(429)
                        self.send_header("Retry-After", "1")
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return None
                # authorization (config.go:815 — after authn/APF, before
                # routing).  Non-resource paths (healthz, version, metrics)
                # stay open, like the reference's system:discovery defaults.
                if server.authorizer is not None and r is not None \
                        and r.resource:
                    if verb == "get":
                        rverb = ("watch" if is_watch
                                 else "get" if r.name else "list")
                    elif verb == "delete" and not r.name:
                        rverb = "deletecollection"
                    else:
                        rverb = verb
                    user, groups = self._identity()
                    attrs = rbaclib.Attributes(
                        user, tuple(groups), rverb, r.resource,
                        r.subresource or "", r.ns or "", r.name or "")
                    if not server.authorizer.authorize(attrs):
                        if ticket:
                            ticket.__exit__()
                        with server._metrics_lock:
                            server.metrics["requests_rejected_total"] += 1
                        self._drain_body()
                        target = r.resource + (
                            f"/{r.subresource}" if r.subresource else "")
                        self._send_json(403, status_error(
                            403, "Forbidden",
                            f"user {user!r} cannot {rverb} {target}"
                            + (f" in namespace {r.ns!r}" if r.ns else "")))
                        self._audit(r, rverb, 403)
                        return None
                return r, ticket

            def _audit(self, r: _Route, verb: str, code: int,
                       obj: dict | None = None) -> None:
                if server.audit is not None and r is not None and r.resource:
                    server.audit.log("ResponseComplete", self._user(), verb,
                                     r.resource, r.ns or "", r.name or "",
                                     code, obj)

            # ---- verbs ----

            def do_GET(self):
                begun = self._begin("get")
                if begun is None:
                    return
                r, ticket = begun
                try:
                    if self._maybe_proxy():
                        return
                    self._do_get(r)
                finally:
                    if ticket:
                        ticket.__exit__()

            def _do_get(self, r: _Route) -> None:
                path = r.path
                if path in ("/healthz", "/readyz", "/livez"):
                    self._send_json(200, {"status": "ok"})
                    return
                if path == "/version":
                    self._send_json(200, {"gitVersion": f"v{__version__}",
                                          "platform": "tpu"})
                    return
                if path == "/configz":
                    self._send_json(200, configz.default_registry.snapshot())
                    return
                if path == "/debug/profile":
                    # collapsed stacks (flamegraph.pl format) from the
                    # process-wide sampling profiler; empty body when
                    # the profiling: stanza never started it
                    from ..component_base import profiling
                    body = profiling.default_host_profiler \
                        .collapsed().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path.startswith("/debug/timeline"):
                    # wave timeline observatory: stage intervals + the
                    # union-derived idle share from the process-wide ring
                    # (component_base/timeline.py); ?format=chrome yields
                    # a Perfetto-loadable Chrome trace, default is JSON.
                    # Empty/disabled when profiling.timeline is off.
                    from ..component_base import timeline as cb_timeline
                    tl = cb_timeline.default_timeline
                    if r.query.get("format", [""])[0] == "chrome":
                        body = json.dumps(tl.to_chrome_trace()).encode()
                    else:
                        body = tl.debug_json().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/debug/traces":
                    # recent batch traces from the process-wide flight
                    # recorder (component_base/tracing.py); empty list
                    # when tracing is off or nothing was sampled
                    from ..component_base import tracing
                    body = tracing.default_tracer_provider \
                        .debug_traces_json().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/metrics":
                    with server._metrics_lock:
                        lines = [f"apiserver_{k} {v}"
                                 for k, v in server.metrics.items()]
                    if server.flow is not None:
                        for name, st in server.flow.stats().items():
                            for k, v in st.items():
                                lines.append(
                                    'apiserver_flowcontrol_%s{priority_level'
                                    '="%s"} %s' % (k, name, v))
                    body = ("\n".join(lines) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self._maybe_discovery(path):
                    return
                if r.resource is None:
                    self._send_json(404, status_error(404, "NotFound", path))
                    return
                try:
                    if r.subresource == "token":
                        self._send_json(405, status_error(
                            405, "MethodNotAllowed",
                            "token requests are POST-only"))
                    elif r.resource == "pods" \
                            and r.subresource in NODE_STREAM_SUBRESOURCES:
                        self._node_stream(r)
                    elif r.query.get("watch", ["false"])[0] == "true":
                        self._serve_watch(r.resource, r.query, r)
                    elif r.name is not None and r.subresource == "scale":
                        is_custom = self._is_custom(r)
                        paths = (server.crds.scale_paths(r.resource)
                                 if is_custom else None)
                        if is_custom and paths is None:
                            # GET and PUT must agree the subresource
                            # doesn't exist when undeclared
                            self._send_json(404, status_error(
                                404, "NotFound",
                                f"{r.resource} has no scale subresource"))
                            return
                        obj = server.store.get(r.resource, r.ns or "", r.name)
                        self._audit(r, "get", 200)
                        if paths is not None:
                            self._send_json(200, _crd_scale(obj, paths))
                        else:
                            self._send_json(200,
                                            _scale_of(obj, r.resource))
                    elif r.name is not None:
                        obj = self._serve_custom(
                            r, server.store.get(r.resource, r.ns or "",
                                                r.name))
                        self._audit(r, "get", 200)
                        self._send_json(200, obj)
                    else:
                        sel = r.query.get("labelSelector", [None])[0]
                        fsel = r.query.get("fieldSelector", [None])[0]
                        items, rv = server.store.list(r.resource, r.ns)
                        if sel:
                            items = [o for o in items
                                     if _matches_selector(o, sel)]
                        if fsel:
                            try:
                                validate_field_selector(fsel)
                                items = [o for o in items
                                         if _matches_field_selector(
                                             o, fsel)]
                            except ValueError as e:
                                self._send_json(400, status_error(
                                    400, "BadRequest", str(e)))
                                return
                        if self._is_custom(r):
                            # one batched ConversionReview, not N
                            items = server.crds.convert_many(
                                r.resource, items,
                                self._custom_version(r))
                        elif self._core_target(r) is not None:
                            items = corever.convert_many(
                                r.resource, items, self._core_target(r))
                        self._audit(r, "list", 200)
                        self._send_json(200, {
                            "kind": "List", "apiVersion": "v1",
                            "metadata": {"resourceVersion": str(rv)},
                            "items": items})
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))
                except kv.TooOldError as e:
                    self._send_json(410, status_error(410, "Expired", str(e)))
                except crdlib.ValidationError as e:
                    # read-path conversion failure (webhook down/refusing)
                    self._send_json(500, status_error(
                        500, "InternalError", str(e)))

            def _maybe_discovery(self, path: str) -> bool:
                """GET /api, /api/v1, /apis[...], /openapi/v2 (endpoints/
                discovery/): resolve groups/versions/resources from the
                server, not a client-side table."""
                from . import discovery as disc
                parts = [p for p in path.split("/") if p]
                doc = None
                if path == "/api":
                    doc = disc.api_versions()
                elif path == "/api/v1":
                    doc = disc.core_resource_list(CLUSTER_SCOPED,
                                                  SCALABLE)
                elif (len(parts) == 2 and parts[0] == "api"
                        and parts[1] in corever.SERVED_VERSIONS):
                    doc = disc.core_versioned_resource_list(
                        parts[1], CLUSTER_SCOPED)
                elif path == "/apis":
                    doc = disc.group_list(
                        BUILTIN_GROUPS, server.crds,
                        extra=server.aggregator.known_group_versions())
                elif path == "/openapi/v3":
                    doc = disc.openapi_v3_index(BUILTIN_GROUPS,
                                                server.crds)
                elif path.startswith("/openapi/v3/"):
                    doc = disc.openapi_v3_group(
                        path[len("/openapi/v3/"):], BUILTIN_GROUPS,
                        CLUSTER_SCOPED, server.crds)
                elif path == "/openapi/v2":
                    doc = disc.openapi_v2(BUILTIN_GROUPS, CLUSTER_SCOPED,
                                          server.crds)
                elif len(parts) == 2 and parts[0] == "apis":
                    doc = disc.api_group(
                        parts[1], BUILTIN_GROUPS, server.crds,
                        extra=server.aggregator.known_group_versions())
                elif len(parts) == 3 and parts[0] == "apis":
                    doc = disc.group_resource_list(
                        parts[1], parts[2], BUILTIN_GROUPS,
                        CLUSTER_SCOPED, SCALABLE, server.crds)
                else:
                    return False
                if doc is None:
                    self._send_json(404, status_error(
                        404, "NotFound", path))
                else:
                    self._send_json(200, doc)
                return True

            def _serve_watch(self, resource: str, q,
                             r: _Route | None = None) -> None:
                raw = q.get("resourceVersion", [""])[0]
                try:
                    since = int(raw) if raw != "" else None
                except ValueError:
                    self._send_json(400, status_error(
                        400, "BadRequest", f"invalid resourceVersion {raw!r}"))
                    return
                fsel = q.get("fieldSelector", [None])[0]
                if fsel:
                    try:
                        validate_field_selector(fsel)
                    except ValueError as e:
                        self._send_json(400, status_error(
                            400, "BadRequest", str(e)))
                        return
                w = server.store.watch(resource, since_rv=since)
                # field-filtered watch: a MODIFIED that ENTERS the
                # selection serves as ADDED, one that LEAVES serves as
                # DELETED (the reference cacher's watchFilter contract —
                # the kubelet's spec.nodeName watch sees its pods
                # "appear" when the scheduler binds them).  Seed the
                # matched set from current state (AFTER the watch is
                # registered, so nothing falls between): a client that
                # listed-then-watched must get leave/delete events for
                # objects that matched before the stream opened.
                fsel_matched: set[str] = set()
                if fsel:
                    seed_items, _seed_rv = server.store.list(resource,
                                                             r.ns if r
                                                             else None)
                    for o in seed_items:
                        if _matches_field_selector(o, fsel):
                            fsel_matched.add(meta.namespaced_name(o))
                with server._metrics_lock:
                    server.metrics["watch_streams"] += 1
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                # plain-identity streams (no field selector, no version
                # conversion) may use the event's shared wire cache:
                # one json.dumps per EVENT instead of one per watcher
                plain_wire = not fsel and (
                    r is None or not (self._is_custom(r)
                                      or self._core_target(r)))
                try:
                    while True:
                        evs = w.next_batch(timeout=5.0)
                        if not evs:
                            if w.stopped:
                                break
                            evs = [None]  # heartbeat below
                        lines = []
                        relist = False
                        if plain_wire:
                            for ev in evs:
                                if ev is None:
                                    lines.append(
                                        '{"type": "BOOKMARK", "object": '
                                        '{"metadata": {}}}\n')
                                    continue
                                wire = ev._wire
                                if wire is None:
                                    wire = json.dumps(
                                        {"type": ev.type,
                                         "object": ev.object}) + "\n"
                                    ev._wire = wire
                                lines.append(wire)
                            if lines:
                                data = "".join(lines).encode()
                                self.wfile.write(
                                    f"{len(data):x}\r\n".encode()
                                    + data + b"\r\n")
                                self.wfile.flush()
                            continue
                        for ev in evs:
                            if ev is None:
                                payload = {"type": kv.BOOKMARK,
                                           "object": {"metadata": {}}}
                            else:
                                obj = ev.object
                                etype = ev.type
                                if fsel:
                                    key = meta.namespaced_name(obj)
                                    hit = _matches_field_selector(obj,
                                                                  fsel)
                                    if etype == kv.DELETED:
                                        if key not in fsel_matched:
                                            continue
                                        fsel_matched.discard(key)
                                    elif hit and key not in fsel_matched:
                                        fsel_matched.add(key)
                                        etype = kv.ADDED  # entered
                                    elif hit:
                                        pass  # stays MODIFIED/ADDED
                                    elif key in fsel_matched:
                                        fsel_matched.discard(key)
                                        etype = kv.DELETED  # left
                                    else:
                                        continue  # never matched
                                if r is not None and (
                                        self._is_custom(r)
                                        or self._core_target(r)):
                                    try:
                                        obj = self._serve_custom(r, obj)
                                    except crdlib.ValidationError:
                                        # conversion webhook failure mid-
                                        # stream: end the watch cleanly
                                        # so the client relists
                                        relist = True
                                        break
                                payload = {"type": etype, "object": obj}
                            lines.append(json.dumps(payload) + "\n")
                        if lines:
                            # a burst is ONE chunk write + flush, not one
                            # syscall pair per event (a 16k-bind batch
                            # fans out to every pod watcher)
                            data = "".join(lines).encode()
                            self.wfile.write(f"{len(data):x}\r\n".encode()
                                             + data + b"\r\n")
                            self.wfile.flush()
                        if relist:
                            break
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    w.stop()
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass
                self.close_connection = True

            # ---- kubelet tunnel (exec/attach/portforward/log) ----

            def _kubelet_endpoint(self, r: _Route):
                """Resolve the pod's kubelet (host, port, pod spec) from
                node status daemonEndpoints, or write the error."""
                try:
                    pod = server.store.get("pods", r.ns or "", r.name)
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound",
                                                      str(e)))
                    return None
                node_name = (pod.get("spec") or {}).get("nodeName")
                if not node_name:
                    self._send_json(400, status_error(
                        400, "BadRequest",
                        f"pod {r.name!r} is not scheduled"))
                    return None
                try:
                    node = server.store.get("nodes", "", node_name)
                except kv.NotFoundError:
                    self._send_json(502, status_error(
                        502, "BadGateway", f"node {node_name!r} gone"))
                    return None
                status = node.get("status") or {}
                port = ((status.get("daemonEndpoints") or {})
                        .get("kubeletEndpoint") or {}).get("Port")
                addr = next((a.get("address")
                             for a in status.get("addresses") or ()
                             if a.get("type") == "InternalIP"), None)
                if not port or not addr:
                    self._send_json(502, status_error(
                        502, "BadGateway",
                        f"node {node_name!r} has no kubelet endpoint"))
                    return None
                return addr, int(port), pod

            def _node_stream(self, r: _Route) -> None:
                """Proxy a pod log/exec/attach/portforward subresource to
                the pod's kubelet.  Plain responses (log) are relayed as a
                stream; 101 upgrades hand the connection over to a blind
                two-way byte pump — the apiserver never parses frames,
                exactly the reference's UpgradeAwareProxy contract."""
                got = self._kubelet_endpoint(r)
                if got is None:
                    return
                addr, port, pod = got
                q = dict(r.query)
                if r.subresource == "portforward":
                    path = f"/portForward/{r.ns}/{r.name}"
                else:
                    container = (q.pop("container", [None]))[0]
                    if container is None:
                        spec = [c["name"] for c in
                                (pod.get("spec") or {}).get("containers")
                                or ()]
                        if len(spec) != 1:
                            self._send_json(400, status_error(
                                400, "BadRequest",
                                "container name required"))
                            return
                        container = spec[0]
                    seg = {"log": "containerLogs"}.get(r.subresource,
                                                      r.subresource)
                    path = f"/{seg}/{r.ns}/{r.name}/{container}"
                query = urlencode([(k, v) for k, vs in q.items()
                                   for v in vs])
                if query:
                    path += "?" + query
                verb = "create" if self.command == "POST" else "get"
                try:
                    upstream = socket.create_connection((addr, port),
                                                        timeout=30.0)
                except OSError as e:
                    self._audit(r, verb, 502)
                    self._send_json(502, status_error(
                        502, "BadGateway", f"kubelet dial failed: {e}"))
                    return
                try:
                    req = [f"{self.command} {path} HTTP/1.1",
                           f"Host: {addr}:{port}"]
                    for h in ("Upgrade", "Connection"):
                        v = self.headers.get(h)
                        if v:
                            req.append(f"{h}: {v}")
                    upstream.sendall(("\r\n".join(req) + "\r\n\r\n")
                                     .encode())
                    # relay the kubelet's response head verbatim
                    head = b""
                    while b"\r\n\r\n" not in head:
                        chunk = upstream.recv(65536)
                        if not chunk:
                            self._audit(r, verb, 502)
                            self._send_json(502, status_error(
                                502, "BadGateway",
                                "kubelet closed during handshake"))
                            return
                        head += chunk
                    # handshake done: an interactive stream may sit idle
                    # far longer than the 30s dial timeout
                    upstream.settimeout(None)
                    head_bytes, _, early = head.partition(b"\r\n\r\n")
                    self.wfile.write(head_bytes + b"\r\n\r\n" + early)
                    self.wfile.flush()
                    is_upgrade = head_bytes.startswith(b"HTTP/1.1 101")
                    try:
                        upstream_code = int(head_bytes.split()[1])
                    except (IndexError, ValueError):
                        upstream_code = 502
                    self._audit(r, verb, upstream_code)
                    self.close_connection = True
                    if is_upgrade:
                        self._pump_sockets(self.connection, upstream)
                    else:
                        self._relay_plain(head_bytes, early, upstream)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    try:
                        upstream.close()
                    except OSError:
                        pass

            def _relay_plain(self, head_bytes: bytes, early: bytes,
                             upstream: socket.socket) -> None:
                """Relay a non-upgrade kubelet response.  Error replies
                are keep-alive with Content-Length — relay exactly that
                many bytes or this thread blocks forever on a socket the
                kubelet never closes.  Length-less responses (log
                streams) relay until EOF, probing the client socket each
                idle beat so an abandoned `logs -f` doesn't pin this
                thread until container exit."""
                import select
                length = None
                for ln in head_bytes.split(b"\r\n")[1:]:
                    k, _, v = ln.partition(b":")
                    if k.strip().lower() == b"content-length":
                        try:
                            length = int(v.strip())
                        except ValueError:
                            pass
                sent = len(early)
                if length is not None and sent >= length:
                    return
                while True:
                    readable, _, _ = select.select(
                        [upstream, self.connection], [], [], 5.0)
                    if self.connection in readable:
                        # half-duplex stream: client bytes here mean EOF
                        try:
                            if self.connection.recv(
                                    1, socket.MSG_PEEK) == b"":
                                return
                        except OSError:
                            return
                    if upstream not in readable:
                        continue
                    chunk = upstream.recv(65536)
                    if not chunk:
                        return
                    self.wfile.write(chunk)
                    self.wfile.flush()
                    sent += len(chunk)
                    if length is not None and sent >= length:
                        return

            @staticmethod
            def _pump_sockets(a: socket.socket, b: socket.socket) -> None:
                """Two-way blind byte pump until either side closes."""
                def one_way(src, dst):
                    try:
                        while True:
                            data = src.recv(65536)
                            if not data:
                                break
                            dst.sendall(data)
                    except OSError:
                        pass
                    for s, how in ((dst, socket.SHUT_WR),
                                   (src, socket.SHUT_RD)):
                        try:
                            s.shutdown(how)
                        except OSError:
                            pass

                t = threading.Thread(target=one_way, args=(b, a),
                                     daemon=True)
                t.start()
                one_way(a, b)
                t.join(timeout=30.0)

            def _read_body(self) -> dict | list | None:
                length = int(self.headers.get("Content-Length", 0))
                try:
                    return json.loads(self.rfile.read(length))
                except (json.JSONDecodeError, ValueError):
                    self._send_json(400, status_error(400, "BadRequest",
                                                      "invalid JSON body"))
                    return None

            def _admit_quiet(self, verb: str, r: _Route, obj: dict,
                             old: dict | None = None,
                             namespace: str | None = None
                             ) -> tuple[dict | None, dict | None]:
                """Run legacy hooks + the admission chain WITHOUT writing
                a response: (admitted_obj, None) or (None, status_error)
                — the bulk paths report per-item.

                `namespace` defaults to the URL namespace (the
                single-object contract: a body claiming another namespace
                must not shift which policy admits it); the bulk path
                passes each item's own namespace explicitly."""
                for hook in server.admission_hooks:
                    try:
                        obj = hook(verb, r.resource, obj) or obj
                    except AdmissionError as e:
                        return None, status_error(400, "AdmissionDenied",
                                                  str(e))
                ident = self._identity() or ("", ())
                attrs = adm.Attributes(
                    verb, r.resource, obj, old,
                    namespace=(namespace if namespace is not None
                               else r.ns or ""),
                    name=r.name or meta.name(obj) or "",
                    subresource=r.subresource or "",
                    user=ident[0], groups=tuple(ident[1]))
                try:
                    server.admission_chain.run(attrs)
                except adm.AdmissionDenied as e:
                    return None, status_error(
                        403, "Forbidden",
                        "admission plugin %s denied the request: %s"
                        % (e.plugin, e))
                return attrs.obj, None

            def _admit(self, verb: str, r: _Route, obj: dict,
                       old: dict | None = None) -> dict | None:
                """Run legacy hooks + the admission chain; None = rejected
                (response already written)."""
                admitted, err = self._admit_quiet(verb, r, obj, old)
                if err is not None:
                    self._send_json(err["code"], err)
                return admitted

            def _is_custom(self, r: _Route) -> bool:
                """CRD-backed resource?  True for BOTH addressing forms:
                the grouped /apis/{g}/{v} path AND the flat /api/v1 path
                (the store is flat, so clients may write custom objects
                there) — a flat-path write must still get the full
                prune/default/validate/CEL pipeline."""
                if r.group in BUILTIN_GROUPS:
                    return False
                return server.crds.lookup(r.resource) is not None

            def _custom_version(self, r: _Route) -> str:
                """The serving version for this request: the URL's on a
                grouped path; the CRD's storage version on the flat path
                (which serves objects in storage form)."""
                if r.group is not None:
                    return r.version
                info = server.crds.lookup(r.resource) or {}
                return info.get("storage_version", r.version)

            def _coerce_custom(self, r: _Route, obj: dict,
                               old: dict | None = None) -> dict | None:
                """Custom-resource write pipeline: prune -> default ->
                schema -> CEL rules, then convert to the CRD's storage
                version (the reference stores ONE version and converts
                on the wire).  None = rejected (422 already sent)."""
                if not self._is_custom(r):
                    if r.group is not None \
                            and r.group not in BUILTIN_GROUPS:
                        # grouped path, no CRD behind it: the resource
                        # does not exist — never silently persist
                        self._send_json(422, status_error(
                            422, "Invalid",
                            f"no CRD for resource {r.resource!r}"))
                        return None
                    tv = self._core_target(r)
                    if tv is not None:
                        # versioned core write: default in the request
                        # version, then convert to the v1 hub for
                        # storage, then hub-side defaulting
                        return corever.default_v1(
                            r.resource,
                            corever.to_storage(r.resource, obj, tv))
                    if r.subresource:
                        return obj  # status/scale splices onto a stored
                        # (already-defaulted) base; nothing to fill
                    # v1 write-time defaulting (defaults.go parity):
                    # idempotent missing-field fills only
                    return corever.default_v1(r.resource, obj)
                try:
                    obj = server.crds.coerce(r.resource,
                                             self._custom_version(r),
                                             obj, old)
                    return server.crds.to_storage(r.resource, obj)
                except crdlib.ValidationError as e:
                    self._send_json(422, status_error(422, "Invalid", str(e)))
                    return None

            def _core_target(self, r: _Route) -> str | None:
                """The non-hub core serving version for this request, or
                None (hub/v1 requests and grouped paths pass through)."""
                if (r.group is None and r.resource
                        and r.version not in (None, corever.HUB)
                        and corever.handles(r.resource, r.version)):
                    return r.version
                return None

            def _serve_custom(self, r: _Route, obj: dict) -> dict:
                """Convert a stored object to the requested serving
                version on the way out (CRDs via the CRD converter, core
                resources via api/core_versions — the same seam)."""
                if self._is_custom(r):
                    return server.crds.convert(r.resource, obj,
                                               self._custom_version(r))
                tv = self._core_target(r)
                if tv is not None:
                    return corever.convert(r.resource, obj, tv)
                return obj

            def do_POST(self):
                begun = self._begin("create")
                if begun is None:
                    return
                r, ticket = begun
                try:
                    if self._maybe_proxy():
                        return
                    self._do_post(r)
                finally:
                    if ticket:
                        ticket.__exit__()

            def _do_post(self, r: _Route) -> None:
                if r.resource is None:
                    self._send_json(404, status_error(404, "NotFound", r.path))
                    return
                if r.resource == "pods" \
                        and r.subresource in NODE_STREAM_SUBRESOURCES:
                    # upgrade requests carry no body — tunnel before any
                    # body read would eat the first stream frames
                    self._node_stream(r)
                    return
                obj = self._read_body()
                if obj is None:
                    return
                # -- subresources --
                if r.subresource == "binding":
                    self._post_binding(r, obj)
                    return
                if r.resource == "bindings":
                    # collection-level Binding (upstream supports a single
                    # POST .../bindings); BindingList extends it to the
                    # batch-scheduler write (store.bind_many, one
                    # transaction) — the front-door equivalent of the
                    # LocalClient bulk bind
                    self._post_bindings(r, obj)
                    return
                if isinstance(obj, dict) and obj.get("kind") == "List" \
                        and isinstance(obj.get("items"), list):
                    self._post_bulk_create(r, obj)
                    return
                if r.subresource == "eviction":
                    self._post_eviction(r, obj)
                    return
                if r.subresource == "token":
                    self._post_token(r, obj)
                    return
                if r.resource == "selfsubjectaccessreviews":
                    # authorization.k8s.io SelfSubjectAccessReview: answer
                    # "can I?" for the REQUESTING identity; never persisted
                    # (pkg/registry/authorization/selfsubjectaccessreview)
                    attrs_spec = ((obj.get("spec") or {})
                                  .get("resourceAttributes") or {})
                    user, groups = self._identity()
                    if server.authorizer is None:
                        allowed, reason = True, "no authorizer configured"
                    else:
                        allowed = server.authorizer.authorize(
                            rbaclib.Attributes(
                                user, tuple(groups),
                                attrs_spec.get("verb", "get"),
                                attrs_spec.get("resource", ""),
                                attrs_spec.get("subresource", ""),
                                attrs_spec.get("namespace", ""),
                                attrs_spec.get("name", "")))
                        reason = ""
                    obj.setdefault("status", {})
                    obj["status"] = {"allowed": bool(allowed),
                                     "reason": reason}
                    self._send_json(201, obj)
                    return
                if r.resource in CLUSTER_SCOPED:
                    if r.ns:
                        self._send_json(400, status_error(
                            400, "BadRequest",
                            f"{r.resource} is cluster-scoped"))
                        return
                    if "metadata" in obj:
                        # stray namespace would fork the storage key away
                        # from the cluster-scoped read path
                        obj["metadata"].pop("namespace", None)
                elif r.ns and "metadata" in obj:
                    obj["metadata"].setdefault("namespace", r.ns)
                obj = self._admit(adm.CREATE, r, obj)
                if obj is None:
                    return
                obj = self._coerce_custom(r, obj)
                if obj is None:
                    return
                if r.resource == crdlib.CRDS:
                    try:
                        obj = server.crds.establish(obj, dry_run=True)
                    except crdlib.ValidationError as e:
                        self._send_json(422, status_error(422, "Invalid",
                                                          str(e)))
                        return
                try:
                    created = server.store.create(r.resource, obj)
                    if r.resource == crdlib.CRDS:
                        server.crds.establish(created)
                    body = self._serve_custom(r, created)
                    self._audit(r, "create", 201, created)
                    self._send_json(201, body)
                except kv.AlreadyExistsError as e:
                    self._send_json(409, status_error(409, "AlreadyExists",
                                                      str(e)))

            def _post_token(self, r: _Route, req: dict) -> None:
                """POST serviceaccounts/{name}/token (TokenRequest,
                pkg/registry/core/serviceaccount/storage/token.go):
                mint a bound SA JWT for an existing account."""
                if server.sa_issuer is None:
                    self._send_json(404, status_error(
                        404, "NotFound",
                        "service account tokens are not enabled"))
                    return
                try:
                    sa = server.store.get("serviceaccounts", r.ns or "",
                                          r.name)
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound",
                                                      str(e)))
                    return
                spec = (req or {}).get("spec") or {}
                try:
                    seconds = int(spec.get("expirationSeconds") or 3600)
                except (TypeError, ValueError):
                    seconds = -1
                if seconds < 600:
                    # token.go: "may not specify a duration less than
                    # 10 minutes" — reject, never silently extend
                    self._send_json(400, status_error(
                        400, "BadRequest",
                        "expirationSeconds must be an integer >= 600"))
                    return
                audiences = tuple(spec.get("audiences") or ())
                token, exp = server.sa_issuer.issue(
                    r.ns or "", r.name, uid=meta.uid(sa) or "",
                    expiration_seconds=seconds, audiences=audiences)
                import time as timelib
                stamp = timelib.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         timelib.gmtime(exp))
                self._audit(r, "create", 201)
                self._send_json(201, {
                    "kind": "TokenRequest",
                    "apiVersion": "authentication.k8s.io/v1",
                    "metadata": {"name": r.name, "namespace": r.ns},
                    "spec": {"expirationSeconds": seconds,
                             "audiences": list(audiences)},
                    "status": {"token": token,
                               "expirationTimestamp": stamp}})

            def _post_bindings(self, r: _Route, body: dict) -> None:
                """POST .../bindings with a Binding (single) or
                BindingList (bulk): each item names its pod
                (metadata.namespace/name) and target node (target.name).
                Bulk rides ONE store transaction (kv.bind_many) — the
                server-side verb that keeps the scheduler's batched
                assignment from serializing into per-pod round trips."""
                if body.get("kind") == "BindingList" \
                        or isinstance(body.get("items"), list):
                    items = body.get("items") or []
                else:
                    items = [body]
                triples = []
                for it in items:
                    md = it.get("metadata") or {}
                    node = ((it.get("target") or {}).get("name")
                            or it.get("nodeName"))
                    if not md.get("name") or not node:
                        self._send_json(400, status_error(
                            400, "BadRequest",
                            "each binding needs metadata.name and "
                            "target.name"))
                        return
                    entry = (md.get("namespace") or r.ns
                             or "default", md["name"], node)
                    if md.get("resourceVersion") is not None:
                        # compare-and-bind precondition (scale-out
                        # schedulers): bind only if the pod hasn't moved
                        entry += (md["resourceVersion"],)
                    triples.append(entry)
                results = server.store.bind_many("pods", triples)
                out = []
                for _obj, err in results:
                    if err is None:
                        out.append({"kind": "Status", "status": "Success"})
                    elif isinstance(err, kv.BindConflict):
                        # distinct reason so HTTP schedulers can classify
                        # lost-the-optimistic-race without string parsing
                        out.append(bind_conflict_status(err))
                    elif isinstance(err, kv.ConflictError):
                        out.append(status_error(409, "Conflict", str(err)))
                    elif isinstance(err, kv.NotFoundError):
                        out.append(status_error(404, "NotFound", str(err)))
                    else:  # pragma: no cover - other store errors
                        out.append(status_error(500, "InternalError",
                                                str(err)))
                self._audit(r, "create", 201)
                self._send_json(201, {"kind": "BindingResultList",
                                      "items": out})

            def _post_bulk_create(self, r: _Route, body: dict) -> None:
                """POST a {kind: List, items: [...]} body on a resource
                collection: per-item admission, then ONE store
                transaction (kv.create_many) with per-item results —
                the bulk sibling of create, used by the event
                broadcaster's flush so a 4096-event burst is one round
                trip, not 4096."""
                if r.resource == crdlib.CRDS:
                    # CRDs need establish() side effects per object; the
                    # singular path is the only one that carries them
                    self._send_json(400, status_error(
                        400, "BadRequest",
                        "bulk create is not supported for "
                        "customresourcedefinitions"))
                    return
                custom = self._is_custom(r)
                items = body.get("items") or []
                prepared: list = []
                statuses: list[dict | None] = []
                for obj in items:
                    md = obj.get("metadata") \
                        if isinstance(obj, dict) else None
                    if not isinstance(md, dict) \
                            or not isinstance(md.get("name"), str):
                        statuses.append(status_error(
                            400, "BadRequest",
                            "item without metadata.name"))
                        prepared.append(None)
                        continue
                    if r.resource in CLUSTER_SCOPED:
                        md.pop("namespace", None)
                    elif r.ns:
                        md.setdefault("namespace", r.ns)
                    try:
                        admitted, err = self._admit_quiet(
                            adm.CREATE, r, obj,
                            namespace=md.get("namespace", ""))
                        core_tv = self._core_target(r)
                        if admitted is not None and core_tv is not None:
                            # versioned core items store in hub form,
                            # same as the singular POST path
                            admitted = corever.to_storage(
                                r.resource, admitted, core_tv)
                        if admitted is not None and not custom:
                            # hub-side v1 defaulting, like the singular
                            # path's _coerce_custom tail
                            admitted = corever.default_v1(r.resource,
                                                          admitted)
                        if admitted is not None and custom:
                            # same prune/default/validate/CEL + storage-
                            # version conversion the singular path runs
                            try:
                                admitted = server.crds.to_storage(
                                    r.resource, server.crds.coerce(
                                        r.resource,
                                        self._custom_version(r),
                                        admitted, None))
                            except crdlib.ValidationError as e:
                                admitted, err = None, status_error(
                                    422, "Invalid", str(e))
                    except Exception as e:  # noqa: BLE001 - per-item wall
                        admitted, err = None, status_error(
                            400, "BadRequest", f"bad item: {e}")
                    if admitted is None:
                        statuses.append(err)
                        prepared.append(None)
                        continue
                    prepared.append(admitted)
                    statuses.append(None)
                live = [o for o in prepared if o is not None]
                results = iter(server.store.create_many(r.resource, live))
                out = []
                for st in statuses:
                    if st is not None:
                        out.append(st)
                        continue
                    created, err = next(results)
                    if err is None:
                        out.append({"kind": "Status", "status": "Success",
                                    "metadata": {
                                        "resourceVersion":
                                        meta.resource_version(created)}})
                    elif isinstance(err, kv.AlreadyExistsError):
                        out.append(status_error(409, "AlreadyExists",
                                                str(err)))
                    else:  # pragma: no cover - other store errors
                        out.append(status_error(500, "InternalError",
                                                str(err)))
                self._audit(r, "create", 201)
                self._send_json(201, {"kind": "CreateResultList",
                                      "items": out})

            def _post_binding(self, r: _Route, binding: dict) -> None:
                """POST pods/{name}/binding (registry/core/pod/storage
                BindingREST): writes spec.nodeName once."""
                node = ((binding.get("target") or {}).get("name")
                        or binding.get("nodeName"))
                expect_rv = (binding.get("metadata")
                             or {}).get("resourceVersion")
                if not node:
                    self._send_json(400, status_error(
                        400, "BadRequest", "binding needs target.name"))
                    return
                try:
                    def bind(pod):
                        if meta.pod_node_name(pod):
                            cur_node = meta.pod_node_name(pod)
                            raise kv.BindConflict(
                                "pod %s is already assigned to node %s"
                                % (r.name, cur_node),
                                key=r.name, current_node=cur_node,
                                wanted_node=node)
                        if expect_rv is not None and \
                                (pod.get("metadata") or {}).get(
                                    "resourceVersion") != expect_rv:
                            raise kv.BindConflict(
                                "pod %s moved past resourceVersion %r"
                                % (r.name, expect_rv),
                                key=r.name, current_node=None,
                                wanted_node=node)
                        pod.setdefault("spec", {})["nodeName"] = node
                        return pod
                    server.store.guaranteed_update(
                        "pods", r.ns or "default", r.name, bind)
                    self._audit(r, "create", 201)
                    self._send_json(201, {"kind": "Status", "status": "Success"})
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))
                except kv.BindConflict as e:
                    self._send_json(409, bind_conflict_status(e))
                except kv.ConflictError as e:
                    self._send_json(409, status_error(409, "Conflict", str(e)))

            def _post_eviction(self, r: _Route, eviction: dict) -> None:
                """POST pods/{name}/eviction (registry/core/pod/storage
                EvictionREST): PDB-gated delete -> 429 when blocked."""
                ns = r.ns or "default"
                try:
                    pod = server.store.get("pods", ns, r.name)
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))
                    return
                try:
                    pdbs, _ = server.store.list("poddisruptionbudgets", ns)
                except Exception:  # noqa: BLE001
                    pdbs = []
                guarding = [p for p in pdbs if _pdb_matches(p, pod)]
                for pdb in guarding:
                    if not _pdb_allows_eviction(server.store, pdb, ns):
                        self._send_json(429, status_error(
                            429, "TooManyRequests",
                            "Cannot evict pod as it would violate the pod's "
                            "disruption budget."))
                        return
                server.store.delete("pods", ns, r.name)
                for pdb in guarding:  # eviction consumes a disruption
                    if "disruptionsAllowed" in (pdb.get("status") or {}):
                        def dec(cur):
                            st = cur.setdefault("status", {})
                            st["disruptionsAllowed"] = max(
                                0, int(st.get("disruptionsAllowed", 0)) - 1)
                            return cur
                        try:
                            server.store.guaranteed_update(
                                "poddisruptionbudgets", ns,
                                (pdb.get("metadata") or {}).get("name"), dec)
                        except kv.NotFoundError:
                            pass
                self._audit(r, "delete", 201)
                self._send_json(201, {"kind": "Status", "status": "Success"})

            def do_PUT(self):
                begun = self._begin("update")
                if begun is None:
                    return
                r, ticket = begun
                try:
                    if self._maybe_proxy():
                        return
                    self._do_put(r)
                finally:
                    if ticket:
                        ticket.__exit__()

            def _do_put(self, r: _Route) -> None:
                if r.resource is None or r.name is None:
                    self._send_json(404, status_error(404, "NotFound", r.path))
                    return
                if r.subresource in VIRTUAL_SUBRESOURCES:
                    # virtual subresources are GET/POST-only — a write
                    # here must never touch the parent object
                    self._drain_body()
                    self._send_json(405, status_error(
                        405, "MethodNotAllowed",
                        f"{r.subresource} does not support this verb"))
                    return
                obj = self._read_body()
                if obj is None:
                    return
                try:
                    if r.subresource == "status":
                        # status strategy: only .status moves (registry
                        # strategies split spec/status writes).  Custom
                        # resources only serve it when their CRD
                        # declares spec.subresources.status
                        # (customresource_handler.go).
                        if self._is_custom(r) \
                                and not server.crds.has_status_subresource(
                                    r.resource):
                            self._send_json(404, status_error(
                                404, "NotFound",
                                f"{r.resource} has no status subresource"))
                            return
                        # status writes pass ADMISSION like any update
                        # (NodeRestriction scopes a kubelet to its own
                        # pods'/node's status; this path used to bypass
                        # the chain entirely)
                        try:
                            old_for_adm = server.store.get(
                                r.resource, r.ns or "", r.name)
                        except kv.StoreError:
                            old_for_adm = None
                        if self._admit(adm.UPDATE, r, obj,
                                       old_for_adm) is None:
                            return
                        new_status = obj.get("status")

                        def set_status(cur):
                            if self._is_custom(r):
                                # the status write passes the same
                                # schema/CEL pipeline as a spec write
                                version = self._custom_version(r)
                                cur = server.crds.convert(
                                    r.resource, cur, version)
                                candidate = dict(cur,
                                                 status=new_status)
                                candidate = server.crds.coerce(
                                    r.resource, version, candidate, cur)
                                return server.crds.to_storage(
                                    r.resource, candidate)
                            tv = self._core_target(r)
                            if tv is not None:
                                # status arrives in the request-version
                                # shape: convert ONLY the status stanza to
                                # hub form and splice it in — a full
                                # convert/default round trip would mutate
                                # .spec from a status endpoint
                                hub_status = corever.to_storage(
                                    r.resource, {"status": new_status},
                                    tv, default=False).get("status")
                                cur["status"] = hub_status
                                return cur
                            cur["status"] = new_status
                            return cur
                        try:
                            updated = server.store.guaranteed_update(
                                r.resource, r.ns or "", r.name,
                                set_status)
                        except crdlib.ValidationError as e:
                            self._send_json(422, status_error(
                                422, "Invalid", str(e)))
                            return
                        body = self._serve_custom(r, updated)
                        self._audit(r, "update", 200)
                        self._send_json(200, body)
                        return
                    if r.subresource == "scale":
                        paths = (server.crds.scale_paths(r.resource)
                                 if self._is_custom(r) else None)
                        if self._is_custom(r) and paths is None:
                            self._send_json(404, status_error(
                                404, "NotFound",
                                f"{r.resource} has no scale subresource"))
                            return
                        replicas = int((obj.get("spec") or {})
                                       .get("replicas", 0))

                        def set_scale(cur):
                            if paths is not None:
                                _set_path(cur, paths.get(
                                    "specReplicasPath",
                                    ".spec.replicas"), replicas)
                            else:
                                cur.setdefault("spec", {})["replicas"] \
                                    = replicas
                            return cur
                        try:
                            updated = server.store.guaranteed_update(
                                r.resource, r.ns or "", r.name,
                                set_scale)
                        except crdlib.ValidationError as e:
                            self._send_json(422, status_error(
                                422, "Invalid", str(e)))
                            return
                        self._audit(r, "update", 200)
                        self._send_json(200, _crd_scale(updated, paths)
                                        if paths is not None
                                        else _scale_of(updated,
                                                       r.resource))
                        return
                    old = None
                    try:
                        old = server.store.get(r.resource, r.ns or "", r.name)
                    except kv.NotFoundError:
                        pass
                    obj = self._admit(adm.UPDATE, r, obj, old)
                    if obj is None:
                        return
                    obj = self._coerce_custom(r, obj, old)
                    if obj is None:
                        return
                    if r.resource == crdlib.CRDS:
                        try:
                            obj = server.crds.establish(obj, dry_run=True)
                        except crdlib.ValidationError as e:
                            self._send_json(422, status_error(
                                422, "Invalid", str(e)))
                            return
                    mflib.track_update(old, obj, self._field_manager())
                    updated = server.store.update(r.resource, obj)
                    if r.resource == crdlib.CRDS:
                        server.crds.establish(updated)
                    body = self._serve_custom(r, updated)
                    self._audit(r, "update", 200, updated)
                    self._send_json(200, body)
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))
                except kv.ConflictError as e:
                    self._send_json(409, status_error(409, "Conflict", str(e)))

            def do_PATCH(self):
                begun = self._begin("patch")
                if begun is None:
                    return
                r, ticket = begun
                try:
                    if self._maybe_proxy():
                        return
                    self._do_patch(r)
                finally:
                    if ticket:
                        ticket.__exit__()

            def _do_patch(self, r: _Route) -> None:
                if r.resource is None or r.name is None:
                    self._send_json(404, status_error(404, "NotFound", r.path))
                    return
                if r.subresource in VIRTUAL_SUBRESOURCES:
                    # virtual subresources are GET/POST-only — a write
                    # here must never touch the parent object
                    self._drain_body()
                    self._send_json(405, status_error(
                        405, "MethodNotAllowed",
                        f"{r.subresource} does not support this verb"))
                    return
                body = self._read_body()
                if body is None:
                    return
                ctype = self.headers.get("Content-Type",
                                         "application/strategic-merge-patch+json")
                if ctype.split(";")[0].strip() == mflib.APPLY_CONTENT_TYPE:
                    self._do_apply(r, body)
                    return
                if r.subresource == "status" and self._is_custom(r) \
                        and not server.crds.has_status_subresource(
                            r.resource):
                    # PUT and PATCH must agree it doesn't exist
                    self._send_json(404, status_error(
                        404, "NotFound",
                        f"{r.resource} has no status subresource"))
                    return
                try:
                    def apply(cur):
                        core_tv = self._core_target(r)
                        hub_cur = cur
                        if self._is_custom(r):
                            # patch against the REQUEST-version shape:
                            # patching the storage form and pruning with
                            # the request schema silently drops fields
                            cur = server.crds.convert(
                                r.resource, cur, self._custom_version(r))
                        elif core_tv is not None:
                            # no defaulting: injected defaults on the
                            # patch base would persist as if user-written
                            cur = corever.convert(r.resource, cur,
                                                  core_tv, default=False)
                        patched = patchlib.apply_patch(ctype, cur, body)
                        if r.subresource == "status":
                            # status patch may only change .status
                            merged = dict(cur)
                            merged["status"] = patched.get("status")
                            patched = merged
                        # resourceVersion comes from the store's CAS loop
                        patched.setdefault("metadata", {})["resourceVersion"] = \
                            (cur.get("metadata") or {}).get("resourceVersion")
                        mflib.track_update(cur, patched,
                                           self._field_manager())
                        # the patched object passes the same gates as a PUT
                        for hook in server.admission_hooks:
                            patched = hook(adm.UPDATE, r.resource,
                                           patched) or patched
                        ident = self._identity() or ("", ())
                        server.admission_chain.run(adm.Attributes(
                            adm.UPDATE, r.resource, patched, cur,
                            namespace=r.ns or "", name=r.name,
                            subresource=r.subresource or "",
                            user=ident[0], groups=tuple(ident[1])))
                        if self._is_custom(r):
                            patched = server.crds.coerce(
                                r.resource, self._custom_version(r),
                                patched, cur)
                            patched = server.crds.to_storage(r.resource,
                                                             patched)
                        elif core_tv is not None:
                            patched = corever.to_storage(
                                r.resource, patched, core_tv,
                                # spec patches get write-time defaulting;
                                # status patches must not touch spec at
                                # all — splice status onto the hub base
                                default=r.subresource != "status")
                            if r.subresource == "status":
                                patched = dict(
                                    hub_cur,
                                    status=patched.get("status"),
                                    metadata=patched.get("metadata"))
                        if r.resource == crdlib.CRDS:
                            patched = server.crds.establish(patched,
                                                            dry_run=True)
                        return patched
                    updated = server.store.guaranteed_update(
                        r.resource, r.ns or "", r.name, apply)
                    if r.resource == crdlib.CRDS:
                        server.crds.establish(updated)
                    body = self._serve_custom(r, updated)
                    self._audit(r, "patch", 200)
                    self._send_json(200, body)
                except (patchlib.PatchError, crdlib.ValidationError) as e:
                    self._send_json(422, status_error(422, "Invalid", str(e)))
                except adm.AdmissionDenied as e:
                    self._send_json(403, status_error(
                        403, "Forbidden",
                        "admission plugin %s denied the request: %s"
                        % (e.plugin, e)))
                except AdmissionError as e:
                    self._send_json(400, status_error(400, "AdmissionDenied",
                                                      str(e)))
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))
                except kv.ConflictError as e:
                    self._send_json(409, status_error(409, "Conflict", str(e)))

            def _field_manager(self, default: str = "unknown") -> str:
                r = self._route()
                vals = r.query.get("fieldManager") if r else None
                return vals[0] if vals else default

            def _do_apply(self, r: _Route, applied: dict) -> None:
                """Server-side apply (PATCH application/apply-patch+yaml):
                create-or-merge driven by managedFields ownership
                (managedfields.py; endpoints/handlers/patch.go applyPatcher)."""
                manager = self._field_manager(default="apply")
                force = (r.query.get("force") or ["false"])[0] == "true"
                applied.setdefault("metadata", {}).setdefault("name", r.name)
                if r.resource in CLUSTER_SCOPED:
                    # stray namespace would fork the storage key away from
                    # the cluster-scoped read path (same strip as POST)
                    applied["metadata"].pop("namespace", None)
                elif r.ns:
                    applied["metadata"].setdefault("namespace", r.ns)
                try:
                    try:
                        live = server.store.get(r.resource, r.ns or "",
                                                r.name)
                    except kv.NotFoundError:
                        live = None
                    if live is None:
                        new = mflib.apply_merge(None, applied, manager)
                        new = self._admit(adm.CREATE, r, new, None)
                        if new is None:
                            return
                        new = self._coerce_custom(r, new)
                        if new is None:
                            return
                        if r.resource == crdlib.CRDS:
                            # a CRD applied (SSA) must establish exactly
                            # like one POSTed, or it never serves
                            new = server.crds.establish(new,
                                                        dry_run=True)
                        try:
                            created = server.store.create(r.resource, new)
                            if r.resource == crdlib.CRDS:
                                server.crds.establish(created)
                        except kv.AlreadyExistsError:
                            # lost the create race to a concurrent first
                            # apply: fall through and MERGE with the
                            # winner (apply-to-existing is well-defined)
                            created = None
                        if created is not None:
                            body = self._serve_custom(r, created)
                            self._audit(r, "apply", 201, created)
                            self._send_json(201, body)
                            return

                    def merge(cur):
                        core_tv = self._core_target(r)
                        if self._is_custom(r):
                            # merge in the request-version shape (see
                            # the PATCH closure's rationale)
                            cur = server.crds.convert(
                                r.resource, cur, self._custom_version(r))
                        elif core_tv is not None:
                            cur = corever.convert(r.resource, cur,
                                                  core_tv, default=False)
                        new = mflib.apply_merge(cur, applied, manager,
                                                force=force)
                        new["metadata"]["resourceVersion"] = \
                            cur["metadata"].get("resourceVersion")
                        ident = self._identity() or ("", ())
                        server.admission_chain.run(adm.Attributes(
                            adm.UPDATE, r.resource, new, cur,
                            namespace=r.ns or "", name=r.name,
                            subresource=r.subresource or "",
                            user=ident[0], groups=tuple(ident[1])))
                        if self._is_custom(r):
                            new = server.crds.coerce(
                                r.resource, self._custom_version(r),
                                new, cur)
                            new = server.crds.to_storage(r.resource, new)
                        elif core_tv is not None:
                            new = corever.to_storage(r.resource, new,
                                                     core_tv,
                                                     default=False)
                        if r.resource == crdlib.CRDS:
                            new = server.crds.establish(new, dry_run=True)
                        return new
                    updated = server.store.guaranteed_update(
                        r.resource, r.ns or "", r.name, merge)
                    if r.resource == crdlib.CRDS:
                        server.crds.establish(updated)
                    body = self._serve_custom(r, updated)
                    self._audit(r, "apply", 200)
                    self._send_json(200, body)
                except mflib.ApplyConflict as e:
                    body = status_error(409, "Conflict", str(e))
                    body["details"] = {"conflicts": [
                        {"manager": m, "field": mflib.path_str(p)}
                        for m, p in e.conflicts]}
                    self._send_json(409, body)
                except adm.AdmissionDenied as e:
                    self._send_json(403, status_error(
                        403, "Forbidden",
                        "admission plugin %s denied the request: %s"
                        % (e.plugin, e)))
                except (patchlib.PatchError, crdlib.ValidationError) as e:
                    self._send_json(422, status_error(422, "Invalid", str(e)))
                except kv.ConflictError as e:
                    self._send_json(409, status_error(409, "Conflict",
                                                      str(e)))
                except kv.AlreadyExistsError as e:
                    self._send_json(409, status_error(409, "AlreadyExists",
                                                      str(e)))

            def do_DELETE(self):
                begun = self._begin("delete")
                if begun is None:
                    return
                r, ticket = begun
                try:
                    if self._maybe_proxy():
                        return
                    self._do_delete(r)
                finally:
                    if ticket:
                        ticket.__exit__()

            def _do_delete(self, r: _Route) -> None:
                if r.resource is None or r.name is None:
                    self._send_json(404, status_error(404, "NotFound", r.path))
                    return
                if r.subresource in VIRTUAL_SUBRESOURCES:
                    # virtual subresources are GET/POST-only — a write
                    # here must never touch the parent object
                    self._drain_body()
                    self._send_json(405, status_error(
                        405, "MethodNotAllowed",
                        f"{r.subresource} does not support this verb"))
                    return
                # the object being deleted rides old_obj so plugins that
                # decide on current state (NodeRestriction: whose node is
                # this pod bound to?) can see it
                try:
                    cur_obj = server.store.get(r.resource, r.ns or "",
                                               r.name)
                except kv.StoreError:
                    cur_obj = None
                if cur_obj is not None or r.resource == "namespaces":
                    # a DELETE of a missing object must fall through to
                    # the registry's 404, not die on a state-dependent
                    # admission verdict (a kubelet retrying a delete the
                    # GC won would otherwise loop on 403 forever).
                    # Namespaces stay admitted even when implicit: the
                    # immortal-namespace guard is name-based.
                    #
                    # INVARIANT (delete admission): attrs.obj is None on
                    # DELETE — only name/namespace/old_obj carry state.
                    # A plugin that denies deletes MUST therefore key on
                    # the NAME (like NamespaceLifecycle's immortal set)
                    # or on old_obj, never on attrs.obj: a deny derived
                    # from attrs.obj can't fire here, silently admitting
                    # exactly the deletes it was written to block.
                    ident = self._identity() or ("", ())
                    attrs = adm.Attributes(adm.DELETE, r.resource, None,
                                           cur_obj,
                                           namespace=r.ns or "",
                                           name=r.name,
                                           user=ident[0],
                                           groups=tuple(ident[1]))
                    try:
                        server.admission_chain.run(attrs)
                    except adm.AdmissionDenied as e:
                        self._send_json(403, status_error(
                            403, "Forbidden", str(e)))
                        return
                try:
                    # DeleteOptions.propagationPolicy: Foreground/Orphan
                    # park the object with the matching finalizer for the
                    # garbage collector (registry store deletion strategy)
                    policy = (r.query.get("propagationPolicy")
                              or [None])[0]
                    fin = meta.propagation_finalizer(policy)
                    if fin is not None:
                        def park(cur, fin=fin):
                            fins = cur["metadata"].setdefault(
                                "finalizers", [])
                            if fin not in fins:
                                fins.append(fin)
                            return cur
                        server.store.guaranteed_update(
                            r.resource, r.ns or "", r.name, park)
                    deleted = server.store.delete(r.resource, r.ns or "",
                                                  r.name)
                    if r.resource == crdlib.CRDS:
                        server.crds.remove(deleted)
                    self._audit(r, "delete", 200)
                    self._send_json(200, deleted)
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))

        return Handler


# -- helpers ---------------------------------------------------------------

def _get_path(obj: dict, path: str):
    """'.spec.replicas'-style JSON path lookup (customresource scale
    paths)."""
    cur = obj
    for part in path.strip(".").split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _set_path(obj: dict, path: str, value) -> None:
    parts = path.strip(".").split(".")
    cur = obj
    for part in parts[:-1]:
        nxt = cur.get(part)
        if nxt is None:
            nxt = cur[part] = {}
        elif not isinstance(nxt, dict):
            raise crdlib.ValidationError(
                f"cannot set {path}: {part!r} is not an object")
        cur = nxt
    cur[parts[-1]] = value


def _crd_scale(obj: dict, paths: dict) -> dict:
    """Scale projection through a CRD's declared subresource paths
    (customresource/status_strategy.go scale handling)."""
    return {"kind": "Scale", "apiVersion": "autoscaling/v1",
            "metadata": {"name": meta.name(obj),
                         "namespace": meta.namespace(obj)},
            "spec": {"replicas": _get_path(
                obj, paths.get("specReplicasPath", ".spec.replicas"))
                or 0},
            "status": {"replicas": _get_path(
                obj, paths.get("statusReplicasPath",
                               ".status.replicas")) or 0,
                       "selector": _get_path(
                obj, paths.get("labelSelectorPath", "")) or ""}}


def _scale_of(obj: dict, resource: str) -> dict:
    """autoscaling/v1 Scale subresource projection."""
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    return {"kind": "Scale", "apiVersion": "autoscaling/v1",
            "metadata": {"name": meta.name(obj),
                         "namespace": meta.namespace(obj)},
            "spec": {"replicas": spec.get("replicas", 0)},
            "status": {"replicas": status.get("replicas", 0),
                       "selector": (spec.get("selector") or {})
                       .get("matchLabels", {})}}


from ..api.fields import matches_field_selector as _matches_field_selector
from ..api.fields import validate_field_selector


def _matches_selector(obj: dict, selector: str) -> bool:
    """labelSelector query param: k=v[,k=v...] equality matching."""
    labels = (obj.get("metadata") or {}).get("labels") or {}
    for part in selector.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            if labels.get(k.strip()) == v.strip():
                return False
        elif "=" in part:
            k, v = part.split("=", 1)
            if labels.get(k.strip()) != v.strip():
                return False
        else:  # existence
            if part not in labels:
                return False
    return True


def _pdb_matches(pdb: dict, pod: dict) -> bool:
    sel = ((pdb.get("spec") or {}).get("selector") or {}).get("matchLabels", {})
    labels = (pod.get("metadata") or {}).get("labels") or {}
    return bool(sel) and all(labels.get(k) == v for k, v in sel.items())


def _parse_intstr(value, expected: int) -> int:
    """IntOrString: '50%' of expected (rounded up for minAvailable-style
    use; upstream uses intstr.GetScaledValueFromIntOrPercent)."""
    if isinstance(value, str) and value.endswith("%"):
        pct = float(value[:-1])
        return -(-int(pct * expected) // 100)  # ceil
    return int(value)


def _expected_count(store: kv.MemoryStore, matching: list, ns: str) -> int:
    """Desired replica count summed over every distinct owning controller
    (the disruption controller reads scale subresources the same way);
    unowned pods count themselves."""
    owners: dict = {}
    unowned = 0
    for p in matching:
        ref = next((r for r in ((p.get("metadata") or {})
                                .get("ownerReferences") or [])
                    if r.get("controller")), None)
        if ref and ref.get("kind") in ("ReplicaSet", "StatefulSet",
                                       "ReplicationController", "Deployment"):
            key = (ref["kind"], ref["name"])
            if key in owners:
                continue
            try:
                owner = store.get(ref["kind"].lower() + "s", ns, ref["name"])
                owners[key] = int((owner.get("spec") or {})
                                  .get("replicas", 1))
            except kv.NotFoundError:
                owners[key] = 0
        else:
            unowned += 1
    if not owners:
        return len(matching)
    return sum(owners.values()) + unowned


def _pdb_allows_eviction(store: kv.MemoryStore, pdb: dict, ns: str) -> bool:
    """Eviction gate (registry/core/pod/storage/eviction.go): prefer the
    disruption controller's status.disruptionsAllowed; otherwise compute
    inline from minAvailable/maxUnavailable (IntOrString, % supported)."""
    status = pdb.get("status") or {}
    if "disruptionsAllowed" in status:
        return int(status["disruptionsAllowed"]) > 0
    spec = pdb.get("spec") or {}
    sel = (spec.get("selector") or {}).get("matchLabels", {})
    pods, _ = store.list("pods", ns)
    matching = [p for p in pods
                if all(((p.get("metadata") or {}).get("labels") or {})
                       .get(k) == v for k, v in sel.items())]
    healthy = sum(1 for p in matching
                  if (p.get("status") or {}).get("phase")
                  not in ("Failed", "Succeeded")
                  and not (p.get("metadata") or {}).get("deletionTimestamp"))
    expected = _expected_count(store, matching, ns)
    if "minAvailable" in spec:
        return healthy - 1 >= _parse_intstr(spec["minAvailable"], expected)
    if "maxUnavailable" in spec:
        max_unavail = _parse_intstr(spec["maxUnavailable"], expected)
        disrupted = max(0, expected - healthy)
        return disrupted + 1 <= max_unavail
    return True
