"""REST+watch API server over the store.

Reference: the kube-apiserver serving stack, reduced to its load-bearing
contract (SURVEY.md layers 4-5):
  staging/src/k8s.io/apiserver/pkg/endpoints/installer.go:190 (routes)
    GET    /api/v1/{resource}                       list (all namespaces)
    GET    /api/v1/namespaces/{ns}/{resource}       list
    GET    /api/v1/namespaces/{ns}/{resource}/{nm}  get
    POST   /api/v1/namespaces/{ns}/{resource}       create
    PUT    /api/v1/namespaces/{ns}/{resource}/{nm}  update (CAS -> 409)
    DELETE /api/v1/namespaces/{ns}/{resource}/{nm}  delete
    GET    ...?watch=true&resourceVersion=N         newline-delimited JSON
                                                    event stream
  plus /healthz /readyz /version /metrics, and a minimal handler chain
  (request log -> authn stub -> admission hooks -> registry), mirroring
  DefaultBuildHandlerChain (server/config.go:813) in shape.

Cluster-scoped resources (nodes, ...) use ns="-" internally; the routes
also accept /api/v1/{resource}/{name} for them.

Errors are metav1.Status-shaped JSON with the right HTTP codes
(404/409/410 Gone for compacted watches).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .. import __version__
from ..api import meta
from ..store import kv

logger = logging.getLogger(__name__)

CLUSTER_SCOPED = {"nodes", "persistentvolumes", "namespaces", "priorityclasses",
                  "storageclasses", "csinodes"}

# admission hook: fn(verb, resource, obj) -> obj (mutate) or raise AdmissionError
AdmissionHook = "callable"


class AdmissionError(Exception):
    pass


def status_error(code: int, reason: str, message: str) -> dict:
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": message, "code": code}


class APIServer:
    def __init__(self, store: kv.MemoryStore, host: str = "127.0.0.1",
                 port: int = 0, token: str | None = None):
        self.store = store
        self.token = token
        self.admission_hooks: list = []
        self.metrics = {"requests_total": 0, "watch_streams": 0}
        self._metrics_lock = threading.Lock()
        handler = self._make_handler()
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "APIServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="apiserver", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()

    @property
    def url(self) -> str:
        return f"http://{self.httpd.server_address[0]}:{self.port}"

    # -- request handling ------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through logging
                logger.debug("apiserver: " + fmt, *args)

            def _send_json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authn(self) -> bool:
                if server.token is None:
                    return True
                auth = self.headers.get("Authorization", "")
                if auth == f"Bearer {server.token}":
                    return True
                self._send_json(401, status_error(401, "Unauthorized",
                                                  "invalid bearer token"))
                return False

            def _route(self):
                """-> (resource, ns, name, query) or None after writing error."""
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                q = parse_qs(u.query)
                if not parts or parts[0] not in ("api",):
                    return None, None, None, q, u.path
                # /api/v1/...
                rest = parts[2:] if len(parts) > 1 else []
                ns = name = None
                resource = None
                if len(rest) >= 2 and rest[0] == "namespaces" and len(rest) >= 3:
                    ns, resource = rest[1], rest[2]
                    name = rest[3] if len(rest) > 3 else None
                elif rest:
                    resource = rest[0]
                    name = rest[1] if len(rest) > 1 else None
                return resource, ns, name, q, u.path

            # ---- verbs ----

            def do_GET(self):
                with server._metrics_lock:
                    server.metrics["requests_total"] += 1
                if not self._authn():
                    return
                path = urlparse(self.path).path
                if path == "/healthz" or path == "/readyz" or path == "/livez":
                    self._send_json(200, {"status": "ok"})
                    return
                if path == "/version":
                    self._send_json(200, {"gitVersion": f"v{__version__}",
                                          "platform": "tpu"})
                    return
                if path == "/metrics":
                    with server._metrics_lock:
                        lines = [f"apiserver_{k} {v}"
                                 for k, v in server.metrics.items()]
                    body = ("\n".join(lines) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                resource, ns, name, q, _ = self._route()
                if resource is None:
                    self._send_json(404, status_error(404, "NotFound", path))
                    return
                try:
                    if q.get("watch", ["false"])[0] == "true":
                        self._serve_watch(resource, q)
                    elif name is not None:
                        obj = server.store.get(resource, ns or "", name)
                        self._send_json(200, obj)
                    else:
                        items, rv = server.store.list(resource, ns)
                        self._send_json(200, {
                            "kind": "List", "apiVersion": "v1",
                            "metadata": {"resourceVersion": str(rv)},
                            "items": items})
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))
                except kv.TooOldError as e:
                    self._send_json(410, status_error(410, "Expired", str(e)))

            def _serve_watch(self, resource: str, q) -> None:
                raw = q.get("resourceVersion", [""])[0]
                try:
                    since = int(raw) if raw != "" else None
                except ValueError:
                    self._send_json(400, status_error(
                        400, "BadRequest", f"invalid resourceVersion {raw!r}"))
                    return
                w = server.store.watch(resource, since_rv=since)
                with server._metrics_lock:
                    server.metrics["watch_streams"] += 1
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while True:
                        ev = w.next(timeout=5.0)
                        if ev is None:
                            if w.stopped:
                                break
                            payload = {"type": kv.BOOKMARK,
                                       "object": {"metadata": {}}}
                        else:
                            payload = {"type": ev.type, "object": ev.object}
                        data = (json.dumps(payload) + "\n").encode()
                        self.wfile.write(f"{len(data):x}\r\n".encode()
                                         + data + b"\r\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    w.stop()
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass
                self.close_connection = True

            def _read_body(self) -> dict | None:
                length = int(self.headers.get("Content-Length", 0))
                try:
                    return json.loads(self.rfile.read(length))
                except (json.JSONDecodeError, ValueError):
                    self._send_json(400, status_error(400, "BadRequest",
                                                      "invalid JSON body"))
                    return None

            def _admit(self, verb: str, resource: str, obj: dict) -> dict | None:
                for hook in server.admission_hooks:
                    try:
                        obj = hook(verb, resource, obj) or obj
                    except AdmissionError as e:
                        self._send_json(400, status_error(
                            400, "AdmissionDenied", str(e)))
                        return None
                return obj

            def do_POST(self):
                with server._metrics_lock:
                    server.metrics["requests_total"] += 1
                if not self._authn():
                    return
                resource, ns, name, q, path = self._route()
                if resource is None:
                    self._send_json(404, status_error(404, "NotFound", path))
                    return
                obj = self._read_body()
                if obj is None:
                    return
                if ns and "metadata" in obj:
                    obj["metadata"].setdefault("namespace", ns)
                obj = self._admit("CREATE", resource, obj)
                if obj is None:
                    return
                try:
                    self._send_json(201, server.store.create(resource, obj))
                except kv.AlreadyExistsError as e:
                    self._send_json(409, status_error(409, "AlreadyExists", str(e)))

            def do_PUT(self):
                with server._metrics_lock:
                    server.metrics["requests_total"] += 1
                if not self._authn():
                    return
                resource, ns, name, q, path = self._route()
                if resource is None or name is None:
                    self._send_json(404, status_error(404, "NotFound", path))
                    return
                obj = self._read_body()
                if obj is None:
                    return
                obj = self._admit("UPDATE", resource, obj)
                if obj is None:
                    return
                try:
                    self._send_json(200, server.store.update(resource, obj))
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))
                except kv.ConflictError as e:
                    self._send_json(409, status_error(409, "Conflict", str(e)))

            def do_DELETE(self):
                with server._metrics_lock:
                    server.metrics["requests_total"] += 1
                if not self._authn():
                    return
                resource, ns, name, q, path = self._route()
                if resource is None or name is None:
                    self._send_json(404, status_error(404, "NotFound", path))
                    return
                try:
                    self._send_json(200, server.store.delete(resource, ns or "", name))
                except kv.NotFoundError as e:
                    self._send_json(404, status_error(404, "NotFound", str(e)))

        return Handler
