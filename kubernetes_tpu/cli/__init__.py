"""CLI (reference: staging/src/k8s.io/kubectl)."""

from .kubectl import Kubectl, run  # noqa: F401
