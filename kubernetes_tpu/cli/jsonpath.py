"""kubectl's JSONPath output dialect — the load-bearing subset.

Reference: staging/src/k8s.io/client-go/util/jsonpath/jsonpath.go
(kubectl -o jsonpath=TEMPLATE).
Supported:
  {.path.to.field}            dotted lookups
  {.items[0].metadata.name}   array indexing
  {.items[*].metadata.name}   wildcard (results joined by spaces)
  {range .items[*]}...{end}   iteration; inner {.x} paths are relative
  {"literal"}                 quoted literals ("\n", "\t" unescaped)
  plain text between expressions passes through

Unsupported syntax raises JSONPathError — a typo'd template must not
silently print nothing.
"""

from __future__ import annotations

import re


class JSONPathError(Exception):
    pass


_TOKEN = re.compile(r"\{([^{}]*)\}")
_STEP = re.compile(r"\.([^.\[\]]+)|\[(\*|-?\d+)\]")


def _walk(nodes: list, path: str) -> list:
    """Apply a path expression ('.a.b[*].c') to a node list."""
    path = path.strip()
    if path in ("", "."):
        return nodes
    if not (path.startswith(".") or path.startswith("[")):
        raise JSONPathError(f"path must start with '.': {path!r}")
    pos = 0
    while pos < len(path):
        m = _STEP.match(path, pos)
        if m is None:
            raise JSONPathError(f"bad path segment at {path[pos:]!r}")
        pos = m.end()
        key, idx = m.group(1), m.group(2)
        out = []
        for n in nodes:
            if key is not None:
                if isinstance(n, dict) and key in n:
                    out.append(n[key])
            elif idx == "*":
                if isinstance(n, list):
                    out.extend(n)
                elif isinstance(n, dict):
                    out.extend(n.values())
            else:
                if isinstance(n, list):
                    try:
                        out.append(n[int(idx)])
                    except IndexError:
                        pass
        nodes = out
    return nodes


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return ""
    if isinstance(v, (dict, list)):
        import json
        return json.dumps(v)
    return str(v)


def evaluate(template: str, obj) -> str:
    """Render a jsonpath template against obj."""
    out: list[str] = []
    pos = 0
    tokens: list[tuple[str, str]] = []  # ("text"|"expr", payload)
    for m in _TOKEN.finditer(template):
        if m.start() > pos:
            tokens.append(("text", template[pos:m.start()]))
        tokens.append(("expr", m.group(1).strip()))
        pos = m.end()
    if pos < len(template):
        tokens.append(("text", template[pos:]))

    def emit(kind: str, payload: str, scope: list) -> None:
        if kind == "text":
            out.append(payload)
        elif payload.startswith('"') and payload.endswith('"'):
            out.append(payload[1:-1]
                       .replace("\\n", "\n").replace("\\t", "\t"))
        else:
            out.append(" ".join(_fmt(v)
                                for v in _walk(scope, payload)))

    i = 0
    while i < len(tokens):
        kind, payload = tokens[i]
        if kind == "expr" and payload.startswith("range"):
            loop_path = payload[len("range"):].strip()
            # find the matching {end} (no nesting in the subset)
            try:
                end = next(j for j in range(i + 1, len(tokens))
                           if tokens[j] == ("expr", "end"))
            except StopIteration:
                raise JSONPathError("range without matching {end}")
            body = tokens[i + 1:end]
            for item in _walk([obj], loop_path):
                for k, p in body:
                    emit(k, p, [item])
            i = end + 1
            continue
        if kind == "expr" and payload == "end":
            raise JSONPathError("{end} without {range}")
        emit(kind, payload, [obj])
        i += 1
    return "".join(out)
