"""kubectl-equivalent CLI.

Reference: staging/src/k8s.io/kubectl/pkg/cmd/ (~40 cobra commands).  The
load-bearing subset: get (table printers, -o json/yaml/wide), describe,
create/apply/delete (-f YAML manifests, multi-doc), scale, cordon/
uncordon, drain, top nodes, logs (hollow runtimes have none; prints
container states), version.  Talks to the REST apiserver via HTTPClient
(--server) so it works against a real multi-process cluster.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import yaml

from .. import __version__
from ..api import meta
from ..client.clientset import CLUSTER_SCOPED_RESOURCES, NODES, PODS, Client
from ..client.http_client import HTTPClient
from ..store import kv

# kind -> resource (for -f manifests); aliases for `get` etc.
KIND_TO_RESOURCE = {
    "Pod": "pods", "Node": "nodes", "Service": "services",
    "Endpoints": "endpoints", "ReplicaSet": "replicasets",
    "Deployment": "deployments", "Job": "jobs", "Namespace": "namespaces",
    "ConfigMap": "configmaps", "Secret": "secrets", "Lease": "leases",
    "PodGroup": "podgroups", "PodDisruptionBudget": "poddisruptionbudgets",
    "Event": "events", "PriorityClass": "priorityclasses",
    "StatefulSet": "statefulsets", "DaemonSet": "daemonsets",
    "CronJob": "cronjobs", "ResourceQuota": "resourcequotas",
    "ServiceAccount": "serviceaccounts", "LimitRange": "limitranges",
    "HorizontalPodAutoscaler": "horizontalpodautoscalers",
    "PersistentVolume": "persistentvolumes",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "StorageClass": "storageclasses",
    "CustomResourceDefinition": "customresourcedefinitions",
}
ALIASES = {
    "po": "pods", "pod": "pods", "no": "nodes", "node": "nodes",
    "svc": "services", "service": "services", "ep": "endpoints",
    "rs": "replicasets", "replicaset": "replicasets",
    "deploy": "deployments", "deployment": "deployments", "job": "jobs",
    "ns": "namespaces", "namespace": "namespaces", "cm": "configmaps",
    "pg": "podgroups", "podgroup": "podgroups", "pdb": "poddisruptionbudgets",
    "ev": "events", "event": "events", "lease": "leases", "pc": "priorityclasses",
    "sts": "statefulsets", "statefulset": "statefulsets",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "cj": "cronjobs", "cronjob": "cronjobs", "quota": "resourcequotas",
    "sa": "serviceaccounts", "serviceaccount": "serviceaccounts",
    "hpa": "horizontalpodautoscalers", "limits": "limitranges",
    "pv": "persistentvolumes", "pvc": "persistentvolumeclaims",
    "sc": "storageclasses", "crd": "customresourcedefinitions",
    "crds": "customresourcedefinitions",
}


# plurals the static tables already know — anything else goes through
# server discovery (every ALIASES value is also a KIND_TO_RESOURCE value)
KNOWN_PLURALS = frozenset(KIND_TO_RESOURCE.values())


def resolve_resource(arg: str) -> str:
    return ALIASES.get(arg.lower(), arg.lower())


def _status_message(body: str) -> str:
    """message out of a Status-shaped error body, else the raw text."""
    try:
        return json.loads(body).get("message", body)
    except (json.JSONDecodeError, AttributeError):
        return body


def age(obj: dict) -> str:
    ts = meta.creation_timestamp(obj)
    if not ts:
        return "<none>"
    d = int(time.time() - ts)
    if d < 120:
        return f"{d}s"
    if d < 7200:
        return f"{d // 60}m"
    if d < 172800:
        return f"{d // 3600}h"
    return f"{d // 86400}d"


def pod_row(p: dict, wide: bool) -> list[str]:
    status = p.get("status") or {}
    phase = status.get("phase", "Pending")
    total = len((p.get("spec") or {}).get("containers") or [])
    run = sum(1 for c in status.get("containerStatuses") or ()
              if c.get("state") == "CONTAINER_RUNNING")
    row = [meta.name(p), f"{run}/{total}", phase, age(p)]
    if wide:
        row += [meta.pod_node_name(p) or "<none>", status.get("podIP", "<none>")]
    return row


def node_row(n: dict, wide: bool) -> list[str]:
    conds = (n.get("status") or {}).get("conditions") or []
    ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                for c in conds)
    status = "Ready" if ready else "NotReady"
    if (n.get("spec") or {}).get("unschedulable"):
        status += ",SchedulingDisabled"
    row = [meta.name(n), status, age(n)]
    if wide:
        alloc = (n.get("status") or {}).get("allocatable") or {}
        row += [alloc.get("cpu", "?"), alloc.get("memory", "?")]
    return row


def generic_row(o: dict, wide: bool) -> list[str]:
    status = o.get("status") or {}
    extra = ""
    if "replicas" in (o.get("spec") or {}):
        extra = (f"{status.get('readyReplicas', 0)}/"
                 f"{(o.get('spec') or {}).get('replicas', 0)}")
    elif "conditions" in status:
        extra = ",".join(c.get("type", "") for c in status["conditions"]
                         if c.get("status") == "True") or "-"
    return [meta.name(o), extra or "-", age(o)]


PRINTERS = {
    "pods": (["NAME", "READY", "STATUS", "AGE"],
             ["NAME", "READY", "STATUS", "AGE", "NODE", "IP"], pod_row),
    "nodes": (["NAME", "STATUS", "AGE"],
              ["NAME", "STATUS", "AGE", "CPU", "MEMORY"], node_row),
}


def print_table(rows: list[list[str]], headers: list[str], out) -> None:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    out.write("  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip() + "\n")
    for r in rows:
        out.write("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip() + "\n")


class Kubectl:
    def __init__(self, client: Client, out=None):
        self.client = client
        self.out = out or sys.stdout
        self._discovery: dict[str, str] | None = None

    # -- get / describe --------------------------------------------------

    def get(self, resource: str, name: str | None, namespace: str,
            output: str | None, selector: str | None = None,
            all_namespaces: bool = False,
            field_selector: str | None = None) -> int:
        resource = self.resolve(resource)
        if name and (selector or all_namespaces or field_selector):
            # matches kubectl: name + -l/-A/--field-selector is a usage
            # error, not a silently-dropped flag
            self.out.write("Error: a resource cannot be retrieved by "
                           "name together with -l/-A/--field-selector\n")
            return 1
        if name:
            try:
                items = [self.client.get(resource, namespace, name)]
            except kv.NotFoundError:
                try:  # cluster-scoped fallback
                    items = [self.client.get(resource, "", name)]
                except kv.NotFoundError as e:
                    self.out.write(f"Error: {e}\n")
                    return 1
        else:
            ns = (None if resource == "nodes" or all_namespaces
                  else namespace)
            items, _ = self.client.list(resource, ns)
            items.sort(key=lambda o: (meta.namespace(o) or "",
                                      meta.name(o)))
        if selector:
            from ..api.labels import parse_selector
            compiled = parse_selector(selector)
            items = [o for o in items if compiled.matches(meta.labels(o))]
        if field_selector:
            from ..api.fields import matches_field_selector
            try:
                items = [o for o in items
                         if matches_field_selector(o, field_selector)]
            except ValueError as e:
                self.out.write(f"error: {e}\n")
                return 1
        if output == "json":
            self.out.write(json.dumps(items if not name else items[0],
                                      indent=2, default=str) + "\n")
            return 0
        if output == "yaml":
            self.out.write(yaml.safe_dump(items if not name else items[0]))
            return 0
        if output == "name":
            # script staple: resource/name lines (cli-runtime -o name)
            for o in items:
                self.out.write(f"{resource}/{meta.name(o)}\n")
            return 0
        if output and output.startswith("jsonpath="):
            from .jsonpath import JSONPathError, evaluate
            root = items[0] if name else {
                "kind": "List", "apiVersion": "v1", "items": items}
            try:
                text = evaluate(output[len("jsonpath="):], root)
            except JSONPathError as e:
                self.out.write(f"error: {e}\n")
                return 1
            self.out.write(text)
            if text and not text.endswith("\n"):
                self.out.write("\n")
            return 0
        if output not in (None, "wide"):
            self.out.write(f"error: unknown output format {output!r}\n")
            return 1
        wide = output == "wide"
        narrow_h, wide_h, rowfn = PRINTERS.get(
            resource, (["NAME", "STATUS", "AGE"], ["NAME", "STATUS", "AGE"],
                       generic_row))
        headers = wide_h if wide else narrow_h
        rows = [rowfn(o, wide) for o in items]
        if all_namespaces:
            headers = ["NAMESPACE"] + headers
            rows = [[meta.namespace(o) or ""] + r
                    for o, r in zip(items, rows)]
        print_table(rows, headers, self.out)
        return 0

    def describe(self, resource: str, name: str, namespace: str) -> int:
        resource = self.resolve(resource)
        try:
            obj = self.client.get(resource, namespace, name)
        except kv.NotFoundError:
            try:
                obj = self.client.get(resource, "", name)
            except kv.NotFoundError as e:
                self.out.write(f"Error: {e}\n")
                return 1
        self.out.write(yaml.safe_dump(obj))
        # related events (describe shows them)
        events, _ = self.client.list("events", namespace)
        related = [e for e in events
                   if (e.get("involvedObject") or {}).get("name") == name]
        if related:
            self.out.write("Events:\n")
            for e in related[-10:]:
                self.out.write(f"  {e.get('type')}\t{e.get('reason')}\t"
                               f"{e.get('message')}\n")
        return 0

    # -- create / apply / delete ----------------------------------------

    def _load_manifests(self, path: str) -> list[dict]:
        with open(path) as f:
            return [d for d in yaml.safe_load_all(f) if d]

    _GEN_FLAGS = {
        "deployment": {"image", "replicas"},
        "configmap": {"from-literal"},
        "secret": {"from-literal"},
        "namespace": set(),
        "service": {"tcp"},
        "job": {"image"},
    }

    def create_generated(self, kind: str, rest: list[str],
                         namespace: str,
                         command: list[str] | None = None) -> int:
        """kubectl create <kind> NAME [flags] [-- CMD...] generators
        (kubectl/pkg/cmd/create/create_*.go): deployment, configmap,
        secret generic, namespace, service clusterip|nodeport, job.
        Unknown flags and stray positionals are errors, like kubectl;
        `command` is everything after a bare `--` (job containers)."""
        allowed = self._GEN_FLAGS.get(kind)
        if allowed is None:
            self.out.write(f"error: unsupported create generator "
                           f"{kind!r}\n")
            return 1

        def flags(args):
            name, out, err = None, {}, None
            i = 0
            while i < len(args):
                a = args[i]
                if a in ("-n", "--namespace"):
                    # argparse.REMAINDER swallowed the global flag;
                    # honor kubectl's canonical trailing placement
                    if i + 1 >= len(args):
                        return None, None, "error: -n needs a value"
                    out["namespace"] = [args[i + 1]]
                    i += 2
                    continue
                if a.startswith("--"):
                    k, eq, v = a[2:].partition("=")
                    if k not in allowed:
                        return None, None, f"error: unknown flag --{k}"
                    if not eq:
                        if i + 1 >= len(args) \
                                or args[i + 1].startswith("--"):
                            return None, None, \
                                f"error: --{k} needs a value"
                        v = args[i + 1]
                        i += 1
                    out.setdefault(k, []).append(v)
                elif name is None:
                    name = a
                else:
                    return None, None, \
                        f"error: unexpected argument {a!r}"
                i += 1
            return name, out, err

        if kind in ("secret", "service"):
            if not rest:
                self.out.write(f"error: create {kind} needs a subtype\n")
                return 1
            subtype, rest = rest[0], rest[1:]
        else:
            subtype = None
        name, fl, err = flags(rest)
        if err:
            self.out.write(err + "\n")
            return 1
        if not name:
            self.out.write("error: NAME is required\n")
            return 1
        if "namespace" in (fl or {}):
            namespace = fl.pop("namespace")[0]

        def literals(key="from-literal"):
            data = {}
            for ent in fl.get(key, ()):
                k, _, v = ent.partition("=")
                data[k] = v
            return data

        def as_int(s: str, flag: str) -> int | None:
            try:
                return int(s)
            except ValueError:
                self.out.write(f"error: --{flag} must be an integer, "
                               f"got {s!r}\n")
                return None

        if kind == "deployment":
            image = (fl.get("image") or [None])[0]
            if not image:
                self.out.write("error: --image is required\n")
                return 1
            replicas = as_int((fl.get("replicas") or ["1"])[0],
                              "replicas")
            if replicas is None:
                return 1
            obj = {"apiVersion": "apps/v1", "kind": "Deployment",
                   "metadata": {"name": name, "namespace": namespace,
                                "labels": {"app": name}},
                   "spec": {
                       "replicas": replicas,
                       "selector": {"matchLabels": {"app": name}},
                       "template": {
                           "metadata": {"labels": {"app": name}},
                           "spec": {"containers": [
                               {"name": name, "image": image}]}}}}
            res = "deployments"
        elif kind == "configmap":
            obj = {"apiVersion": "v1", "kind": "ConfigMap",
                   "metadata": {"name": name, "namespace": namespace},
                   "data": literals()}
            res = "configmaps"
        elif kind == "secret" and subtype == "generic":
            import base64
            obj = {"apiVersion": "v1", "kind": "Secret",
                   "metadata": {"name": name, "namespace": namespace},
                   "type": "Opaque",
                   "data": {k: base64.b64encode(v.encode()).decode()
                            for k, v in literals().items()}}
            res = "secrets"
        elif kind == "namespace":
            obj = {"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": name}}
            res = "namespaces"
        elif kind == "service" and subtype in ("clusterip", "nodeport"):
            if not fl.get("tcp"):
                self.out.write("error: at least one --tcp=PORT[:TARGET] "
                               "is required\n")
                return 1
            ports = []
            for spec in fl.get("tcp", ()):
                port_s, _, target_s = spec.partition(":")
                port = as_int(port_s, "tcp")
                target = as_int(target_s, "tcp") if target_s else port
                if port is None or target is None:
                    return 1
                ports.append({"port": port, "protocol": "TCP",
                              "targetPort": target})
            obj = {"apiVersion": "v1", "kind": "Service",
                   "metadata": {"name": name, "namespace": namespace,
                                "labels": {"app": name}},
                   "spec": {"selector": {"app": name},
                            "type": ("NodePort" if subtype == "nodeport"
                                     else "ClusterIP"),
                            "ports": ports}}
            res = "services"
        elif kind == "job":
            image = (fl.get("image") or [None])[0]
            if not image:
                self.out.write("error: --image is required\n")
                return 1
            container = {"name": name, "image": image}
            if command:
                container["command"] = list(command)
            obj = {"apiVersion": "batch/v1", "kind": "Job",
                   "metadata": {"name": name, "namespace": namespace},
                   "spec": {"template": {
                       "metadata": {"labels": {"job-name": name}},
                       "spec": {"restartPolicy": "Never",
                                "containers": [container]}}}}
            res = "jobs"
        else:
            self.out.write(f"error: unsupported create generator "
                           f"{kind!r}"
                           + (f" {subtype!r}" if subtype else "") + "\n")
            return 1
        try:
            created = self.client.create(res, obj)
        except kv.AlreadyExistsError:
            self.out.write(f"Error: {res}/{name} already exists\n")
            return 1
        self.out.write(f"{res}/{meta.name(created)} created\n")
        return 0

    def create(self, path: str, namespace: str) -> int:
        for obj in self._load_manifests(path):
            res = self._kind_to_resource(obj.get("kind", ""))
            if not res:
                self.out.write(f"error: unknown kind {obj.get('kind')}\n")
                return 1
            obj.setdefault("metadata", {})
            if res not in CLUSTER_SCOPED_RESOURCES:
                # real kubectl never stamps a namespace onto a
                # cluster-scoped object (it would fork the storage key)
                obj["metadata"].setdefault("namespace", namespace)
            try:
                created = self.client.create(res, obj)
                self.out.write(f"{res}/{meta.name(created)} created\n")
            except kv.AlreadyExistsError:
                self.out.write(f"{res}/{meta.name(obj)} already exists\n")
                return 1
            if res == "customresourcedefinitions":
                # the next manifest may be an instance of this CRD
                self._discovery = None
        return 0

    def kustomize(self, directory: str) -> int:
        """kubectl kustomize DIR: print the resolved object stream
        (kustomize build)."""
        from .kustomize import KustomizeError, build
        try:
            objs = build(directory)
        except KustomizeError as e:
            self.out.write(f"error: {e}\n")
            return 1
        self.out.write(yaml.safe_dump_all(objs, sort_keys=False))
        return 0

    def apply_kustomize(self, directory: str, namespace: str,
                        force: bool = False) -> int:
        """kubectl apply -k DIR: kustomize build, then server-side
        apply the resolved objects (kubectl/pkg/cmd/apply with -k)."""
        from .kustomize import KustomizeError, build
        try:
            objs = build(directory)
        except KustomizeError as e:
            self.out.write(f"error: {e}\n")
            return 1
        return self._apply_objs(objs, namespace, force)

    def apply(self, path: str, namespace: str, force: bool = False) -> int:
        """Server-side apply: each manifest is merged by managedFields
        ownership under the 'kubectl' field manager; conflicting fields
        owned by other managers abort with the reference's remediation
        hint unless --force-conflicts (kubectl pkg/cmd/apply with
        --server-side semantics — the only apply mode here; fields you
        stop applying are removed server-side)."""
        return self._apply_objs(self._load_manifests(path), namespace,
                                force)

    def _apply_objs(self, objs: list[dict], namespace: str,
                    force: bool = False) -> int:
        for obj in objs:
            res = self._kind_to_resource(obj.get("kind", ""))
            if not res:
                self.out.write(f"error: unknown kind {obj.get('kind')}\n")
                return 1
            obj.setdefault("metadata", {})
            if res not in CLUSTER_SCOPED_RESOURCES:
                # real kubectl never stamps a namespace onto a
                # cluster-scoped object (it would fork the storage key)
                obj["metadata"].setdefault("namespace", namespace)
            ns, nm = meta.namespace(obj), meta.name(obj)
            try:
                self.client.get(res, ns, nm)
                verb = "configured"
            except kv.NotFoundError:
                verb = "created"
            try:
                self.client.apply(res, obj, field_manager="kubectl",
                                  force=force)
            except kv.ConflictError as e:
                self.out.write(
                    f"error: {e}\n"
                    "hint: overwrite with --force-conflicts, or stop "
                    "managing the conflicting fields\n")
                return 1
            if res == "customresourcedefinitions":
                # the next manifest may be an instance of this CRD
                self._discovery = None
            self.out.write(f"{res}/{nm} {verb}\n")
        return 0

    def delete(self, resource: str, name: str, namespace: str) -> int:
        resource = self.resolve(resource)
        try:
            self.client.delete(resource, namespace, name)
        except kv.NotFoundError:
            try:
                self.client.delete(resource, "", name)
            except kv.NotFoundError as e:
                self.out.write(f"Error: {e}\n")
                return 1
        self.out.write(f"{resource}/{name} deleted\n")
        return 0

    def delete_file(self, path: str, namespace: str) -> int:
        """kubectl delete -f FILE: every object in the manifest stream."""
        rc = 0
        for obj in self._load_manifests(path):
            res = self._kind_to_resource(obj.get("kind", ""))
            if not res:
                self.out.write(f"error: unknown kind {obj.get('kind')}\n")
                rc = 1
                continue
            ns = (obj.get("metadata") or {}).get("namespace") or namespace
            nm = meta.name(obj)
            try:
                self.client.delete(res, ns, nm)
                self.out.write(f"{res}/{nm} deleted\n")
            except kv.NotFoundError as e:
                self.out.write(f"Error: {e}\n")
                rc = 1
        return rc

    def delete_selector(self, resource: str, selector: str,
                        namespace: str) -> int:
        """kubectl delete RESOURCE -l SELECTOR (cli-runtime's selector
        deletes)."""
        from ..api.labels import parse_selector
        resource = self.resolve(resource)
        compiled = parse_selector(selector)
        ns = None if resource in ("nodes",) else namespace
        items, _ = self.client.list(resource, ns)
        victims = [o for o in items if compiled.matches(meta.labels(o))]
        if not victims:
            self.out.write("No resources found\n")
            return 0
        for o in victims:
            try:
                self.client.delete(resource, meta.namespace(o) or "",
                                   meta.name(o))
                self.out.write(f"{resource}/{meta.name(o)} deleted\n")
            except kv.NotFoundError:
                pass  # raced another deleter; outcome identical
        return 0

    # -- scale / cordon / drain / top ------------------------------------

    def scale(self, resource: str, name: str, namespace: str, replicas: int) -> int:
        resource = self.resolve(resource)

        def patch(o):
            o.setdefault("spec", {})["replicas"] = replicas
            return o
        try:
            self.client.guaranteed_update(resource, namespace, name, patch)
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        self.out.write(f"{resource}/{name} scaled to {replicas}\n")
        return 0

    def cordon(self, node: str, on: bool = True) -> int:
        def patch(n):
            n.setdefault("spec", {})["unschedulable"] = on
            if not on:
                n["spec"].pop("unschedulable", None)
            return n
        try:
            self.client.guaranteed_update(NODES, "", node, patch)
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        self.out.write(f"node/{node} {'cordoned' if on else 'uncordoned'}\n")
        return 0

    def drain(self, node: str) -> int:
        rc = self.cordon(node, True)
        if rc:
            return rc
        pods, _ = self.client.list(PODS)
        for p in pods:
            if meta.pod_node_name(p) == node:
                try:
                    self.client.delete(PODS, meta.namespace(p), meta.name(p))
                    self.out.write(f"pod/{meta.name(p)} evicted\n")
                except kv.NotFoundError:
                    pass
        return 0

    def top_nodes(self) -> int:
        from ..api.resources import node_allocatable, pod_request
        nodes, _ = self.client.list(NODES)
        pods, _ = self.client.list(PODS)
        rows = []
        for n in sorted(nodes, key=meta.name):
            alloc = node_allocatable(n)
            used_cpu = used_mem = 0
            for p in pods:
                if meta.pod_node_name(p) == meta.name(n):
                    r = pod_request(p)
                    used_cpu += r.milli_cpu
                    used_mem += r.memory
            cpu_pct = (100 * used_cpu // alloc.milli_cpu) if alloc.milli_cpu else 0
            mem_pct = (100 * used_mem // alloc.memory) if alloc.memory else 0
            rows.append([meta.name(n), f"{used_cpu}m", f"{cpu_pct}%",
                         f"{used_mem // (1 << 20)}Mi", f"{mem_pct}%"])
        print_table(rows, ["NAME", "CPU", "CPU%", "MEMORY", "MEMORY%"], self.out)
        return 0

    def top_pods(self, namespace: str,
                 all_namespaces: bool = False) -> int:
        """kubectl top pods (kubectl/pkg/cmd/top): requested resources
        per pod — the hollow runtime executes nothing, so requests ARE
        the usage signal, exactly what the scheduler accounts."""
        from ..api.resources import pod_request
        pods, _ = self.client.list(PODS, None if all_namespaces
                                   else namespace)
        rows = []
        for p in sorted(pods, key=lambda o: (meta.namespace(o) or "",
                                             meta.name(o))):
            r = pod_request(p)
            row = [meta.name(p), f"{r.milli_cpu}m",
                   f"{r.memory // (1 << 20)}Mi"]
            if all_namespaces:
                row.insert(0, meta.namespace(p) or "")
            rows.append(row)
        headers = ["NAME", "CPU(cores)", "MEMORY(bytes)"]
        if all_namespaces:
            headers = ["NAMESPACE"] + headers
        print_table(rows, headers, self.out)
        return 0

    def logs(self, name: str, namespace: str, container: str | None = None,
             follow: bool = False, tail: int | None = None) -> int:
        """Container logs via the apiserver's kubelet tunnel
        (kubectl/pkg/cmd/logs); falls back to printing container states
        when no kubelet endpoint serves the pod (LocalClient clusters)."""
        http = self._http_client()
        if http is not None:
            q = []
            if container:
                q.append(("container", container))
            if follow:
                q.append(("follow", "true"))
            if tail is not None:
                q.append(("tailLines", str(tail)))
            from urllib.parse import urlencode
            path = (f"/api/v1/namespaces/{namespace}/pods/{name}/log"
                    + ("?" + urlencode(q) if q else ""))
            from ..client.http_client import make_connection
            # no socket timeout: -f follows a stream that may stay
            # silent indefinitely; the server closing ends the read
            conn = make_connection(http.host, http.port,
                                   getattr(http, "_ssl_context", None))
            try:
                conn.request("GET", path, headers=http._headers)
                resp = conn.getresponse()
                if resp.status == 200:
                    while True:
                        chunk = resp.read(4096)
                        if not chunk:
                            return 0
                        self.out.write(chunk.decode(errors="replace"))
                        try:
                            self.out.flush()
                        except (AttributeError, OSError):
                            pass
                body = resp.read().decode(errors="replace")
                if resp.status != 502:
                    self.out.write(f"Error: {_status_message(body)}\n")
                    return 1
                # 502: no kubelet endpoint behind this pod — fall
                # through to the container-state print
            finally:
                conn.close()
        try:
            pod = self.client.get(PODS, namespace, name)
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        for c in (pod.get("status") or {}).get("containerStatuses") or ():
            self.out.write(f"[{c.get('name')}] state={c.get('state')} "
                           f"exitCode={c.get('exitCode')}\n")
        return 0

    # -- discovery-driven commands ----------------------------------------

    def api_versions(self) -> int:
        """kubectl api-versions: every served groupVersion, sorted
        (kubectl/pkg/cmd/apiresources/apiversions.go)."""
        http = self._http_client()
        if http is None:
            self.out.write("Error: this command needs --server\n")
            return 1
        gvs = ["v1"]
        try:
            for g in self.client._request("GET", "/apis").get("groups") or ():
                for v in g.get("versions") or ():
                    if v.get("groupVersion"):
                        gvs.append(v["groupVersion"])
        except (kv.StoreError, OSError) as e:
            self.out.write(f"Error: {e}\n")
            return 1
        for gv in sorted(set(gvs)):
            self.out.write(gv + "\n")
        return 0

    def api_resources(self, namespaced: bool | None = None) -> int:
        """kubectl api-resources: the server's resource tables
        (kubectl/pkg/cmd/apiresources/apiresources.go)."""
        http = self._http_client()
        if http is None:
            self.out.write("Error: this command needs --server\n")
            return 1
        rows: list[list[str]] = []

        def collect(gv: str, resources) -> None:
            for e in resources or ():
                if "/" in e.get("name", ""):
                    continue  # subresources are not rows
                if namespaced is not None \
                        and bool(e.get("namespaced")) != namespaced:
                    continue
                rows.append([e.get("name", ""),
                             ",".join(e.get("shortNames") or ()),
                             gv, str(bool(e.get("namespaced"))).lower(),
                             e.get("kind", "")])
        try:
            collect("v1", self.client._request(
                "GET", "/api/v1").get("resources"))
            for g in self.client._request("GET", "/apis").get("groups") or ():
                for v in g.get("versions") or ():
                    gv = v.get("groupVersion")
                    if not gv:
                        continue
                    try:
                        collect(gv, self.client._request(
                            "GET", f"/apis/{gv}").get("resources"))
                    except (kv.StoreError, OSError):
                        continue
        except (kv.StoreError, OSError) as e:
            self.out.write(f"Error: {e}\n")
            return 1
        rows.sort(key=lambda r: (r[2], r[0]))
        print_table(rows, ["NAME", "SHORTNAMES", "APIVERSION", "NAMESPACED",
                           "KIND"], self.out)
        return 0

    def explain(self, dotted: str) -> int:
        """kubectl explain pod[.spec.containers...]: field docs from the
        server's OpenAPI definitions (kubectl/pkg/cmd/explain over
        /openapi/v2 — CRDs carry their real openAPIV3Schema)."""
        http = self._http_client()
        if http is None:
            self.out.write("Error: this command needs --server\n")
            return 1
        first, _, rest = dotted.partition(".")
        resource = self.resolve(first)
        try:
            spec = self.client._request("GET", "/openapi/v2")
        except (kv.StoreError, OSError) as e:
            self.out.write(f"Error: {e}\n")
            return 1
        defs = spec.get("definitions") or {}
        hit_key, schema = None, None
        dmap = self._discovery_map()
        for key, d in defs.items():
            for gvk in d.get("x-kubernetes-group-version-kind") or ():
                kind = gvk.get("kind", "").lower()
                plural = dmap.get(kind) or KIND_TO_RESOURCE.get(
                    gvk.get("kind", ""), kind + "s")
                if resource in (plural, kind):
                    hit_key, schema = key, d
                    break
            if schema is not None:
                break
        if schema is None:
            self.out.write(
                f"error: couldn't find resource for {first!r}\n")
            return 1
        path = [p for p in rest.split(".") if p]
        # definition keys are "<gv>.<Kind>" where gv may itself be dotted
        # (CRD groups are domain-shaped: "example.com/v1.Widget")
        gv, _, kind_part = hit_key.rpartition(".")
        walked = ["KIND:     " + kind_part,
                  "VERSION:  " + (gv.rpartition("/")[2] if gv else "")]
        for fieldname in path:
            props = schema.get("properties") or {}
            nxt = props.get(fieldname)
            if nxt is None:
                self.out.write(
                    f"error: field {fieldname!r} does not exist\n")
                return 1
            # arrays explain their item schema (kubectl does the same)
            while nxt.get("type") == "array" and "items" in nxt:
                nxt = nxt["items"]
            ref = nxt.get("$ref", "")
            if ref.startswith("#/definitions/"):
                nxt = {**defs.get(ref[len("#/definitions/"):], {}),
                       "description": nxt.get("description", "")}
            schema = nxt
        self.out.write(walked[0] + "\n")
        if walked[1]:
            self.out.write(walked[1] + "\n")
        if path:
            self.out.write("FIELD:    " + path[-1]
                           + f" <{schema.get('type', 'Object')}>\n")
        self.out.write("\nDESCRIPTION:\n     "
                       + (schema.get("description")
                          or "<no description>") + "\n")
        props = schema.get("properties") or {}
        if props:
            self.out.write("\nFIELDS:\n")
            for fname in sorted(props):
                fs = props[fname]
                ftype = fs.get("type") or (
                    "Object" if "$ref" in fs else "Object")
                if ftype == "array":
                    items = fs.get("items") or {}
                    ftype = f"[]{items.get('type', 'Object')}"
                self.out.write(f"   {fname}\t<{ftype}>\n")
                desc = fs.get("description")
                if desc:
                    self.out.write(f"     {desc}\n")
        return 0

    # -- expose / autoscale / set -----------------------------------------

    def expose(self, resource: str, name: str, namespace: str,
               port: int, target_port: int | None = None,
               svc_name: str | None = None, svc_type: str = "ClusterIP",
               protocol: str = "TCP") -> int:
        """kubectl expose: derive a Service selector from the exposed
        object (kubectl/pkg/cmd/expose/exposeservice.go)."""
        resource = self.resolve(resource)
        try:
            obj = self.client.get(resource, namespace, name)
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        spec = obj.get("spec") or {}
        if resource == "services":
            selector = spec.get("selector") or {}
        elif resource == "pods":
            selector = meta.labels(obj)
        else:  # deployments / replicasets / jobs ...: their pod selector
            selector = ((spec.get("selector") or {}).get("matchLabels")
                        or (spec.get("template") or {}).get(
                            "metadata", {}).get("labels") or {})
        if not selector:
            self.out.write(f"error: couldn't find a selector on "
                           f"{resource}/{name}\n")
            return 1
        svc = {
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": svc_name or name, "namespace": namespace,
                         "labels": dict(meta.labels(obj))},
            "spec": {"selector": dict(selector), "type": svc_type,
                     "ports": [{"port": port, "protocol": protocol,
                                "targetPort": target_port or port}]},
        }
        try:
            created = self.client.create("services", svc)
        except kv.AlreadyExistsError:
            self.out.write(f"Error: services/{svc_name or name} already "
                           "exists\n")
            return 1
        self.out.write(f"service/{meta.name(created)} exposed\n")
        return 0

    def autoscale(self, resource: str, name: str, namespace: str,
                  min_replicas: int, max_replicas: int,
                  cpu_percent: int | None = None) -> int:
        """kubectl autoscale: create an HPA targeting the object
        (kubectl/pkg/cmd/autoscale/autoscale.go)."""
        resource = self.resolve(resource)
        try:
            obj = self.client.get(resource, namespace, name)
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        hpa = {
            "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "scaleTargetRef": {
                    "apiVersion": obj.get("apiVersion", "apps/v1"),
                    # stored objects may lack 'kind'; resolve through the
                    # static table so casing matches SCALE_TARGETS
                    # (Statefulset != StatefulSet)
                    "kind": obj.get("kind") or next(
                        (k for k, r in KIND_TO_RESOURCE.items()
                         if r == resource), resource[:-1].title()),
                    "name": name},
                "minReplicas": min_replicas, "maxReplicas": max_replicas,
            },
        }
        if cpu_percent is not None:
            hpa["spec"]["metrics"] = [{
                "type": "Resource",
                "resource": {"name": "cpu", "target": {
                    "type": "Utilization",
                    "averageUtilization": cpu_percent}}}]
        try:
            self.client.create("horizontalpodautoscalers", hpa)
        except kv.AlreadyExistsError:
            self.out.write(f"Error: horizontalpodautoscalers/{name} "
                           "already exists\n")
            return 1
        self.out.write(f"horizontalpodautoscaler/{name} autoscaled\n")
        return 0

    def set_cmd(self, what: str, resource: str, name: str, namespace: str,
                kvs: list[str]) -> int:
        """kubectl set image|env (kubectl/pkg/cmd/set): guaranteed-update
        the workload's pod template containers."""
        resource = self.resolve(resource)
        if what not in ("image", "env"):
            self.out.write(f"error: unknown set subcommand {what!r}\n")
            return 1
        pairs = []
        for s in kvs:
            k, sep, v = s.partition("=")
            if not sep:
                self.out.write(f"error: expected KEY=VALUE, got {s!r}\n")
                return 1
            pairs.append((k, v))

        def containers_of(o):
            if resource == "pods":
                return (o.get("spec") or {}).get("containers") or []
            return (((o.get("spec") or {}).get("template") or {})
                    .get("spec", {}).get("containers") or [])

        def patch(o):
            cs = containers_of(o)
            if what == "image":
                for cname, img in pairs:
                    hit = False
                    for c in cs:
                        if cname == "*" or c.get("name") == cname:
                            c["image"] = img
                            hit = True
                    if not hit:
                        raise ValueError(f"container {cname!r} not found")
            else:
                for c in cs:
                    env = c.setdefault("env", [])
                    for k, v in pairs:
                        env[:] = [e for e in env if e.get("name") != k]
                        env.append({"name": k, "value": v})
            return o
        try:
            self.client.guaranteed_update(resource, namespace, name, patch)
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        except ValueError as e:
            self.out.write(f"error: {e}\n")
            return 1
        self.out.write(f"{resource}/{name} {what} updated\n")
        return 0

    # -- interactive streams (exec / attach / port-forward) ---------------

    def _http_client(self):
        """The HTTPClient behind this kubectl, or None (LocalClient)."""
        return self.client if isinstance(self.client, HTTPClient) else None

    def resolve(self, arg: str) -> str:
        """Resource name from an alias/kind/plural: the static table
        first, then SERVER discovery (/api/v1, /apis/{g}/{v}) — which is
        how CRD-defined kinds and shortNames resolve without kubectl
        knowing them (kubectl/pkg/cmd/util restmapper over discovery)."""
        got = resolve_resource(arg)
        if got in KNOWN_PLURALS:
            return got
        return self._discovery_map().get(got, got)

    def _discovery_map(self) -> dict[str, str]:
        if self._discovery is None and self._http_client() is not None:
            # cache only a successful load — an empty map means the
            # fetch failed, and must not poison later lookups
            self._discovery = self._load_discovery() or None
        return self._discovery or {}

    def _load_discovery(self) -> dict[str, str]:
        """alias/kind/singular/shortName -> plural, from the server."""
        mapping: dict[str, str] = {}
        try:
            for entry in self.client._request(
                    "GET", "/api/v1").get("resources") or ():
                self._index_resource(mapping, entry)
            groups = self.client._request("GET", "/apis").get(
                "groups") or ()
        except (kv.StoreError, OSError):
            return mapping
        for g in groups:
            # every served version, not just preferred: a kind can live
            # exclusively at v1alpha1 while v1 is the group's preferred
            for v in g.get("versions") or ():
                gv = v.get("groupVersion")
                if not gv:
                    continue
                try:
                    rl = self.client._request("GET", f"/apis/{gv}")
                except (kv.StoreError, OSError):
                    continue  # one unhealthy group must not sink the rest
                for entry in rl.get("resources") or ():
                    self._index_resource(mapping, entry)
        return mapping

    def _kind_to_resource(self, kind: str) -> str:
        """Manifest kind -> resource, via the static table then server
        discovery (a just-applied CRD's kind resolves in the same
        kubectl run: writing a CRD invalidates the discovery cache)."""
        return (KIND_TO_RESOURCE.get(kind)
                or self._discovery_map().get(kind.lower(), ""))

    @staticmethod
    def _index_resource(mapping: dict[str, str], entry: dict) -> None:
        plural = entry.get("name", "")
        if "/" in plural:  # subresources don't resolve as resources
            return
        mapping[plural] = plural
        if entry.get("kind"):
            mapping.setdefault(entry["kind"].lower(), plural)
        if entry.get("singularName"):
            mapping.setdefault(entry["singularName"], plural)
        for short in entry.get("shortNames") or ():
            mapping.setdefault(short, plural)

    def _open_stream(self, path: str):
        from ..kubelet import streams
        http = self._http_client()
        if http is None:
            self.out.write("Error: this command needs --server "
                           "(interactive streams ride the HTTP API)\n")
            return None
        try:
            return streams.open_upgrade(
                http.host, http.port, path, headers=http._headers,
                ssl_context=getattr(http, "_ssl_context", None))
        except streams.StreamError as e:
            self.out.write(f"Error: {e}\n")
            return None

    def exec(self, name: str, namespace: str, command: list[str],
             container: str | None = None, stdin: bytes | None = None,
             interactive: bool = False, tty: bool = False,
             err=None) -> int:
        """kubectl exec (kubectl/pkg/cmd/exec/exec.go): POST the exec
        subresource, upgrade, pump channels.  `stdin` carries input bytes
        (CLI -i reads the real stdin)."""
        from urllib.parse import urlencode

        from ..kubelet import streams
        q = [("command", c) for c in command] + [("stdout", "true"),
                                                 ("stderr", "true")]
        if container:
            q.append(("container", container))
        if interactive or stdin is not None:
            q.append(("stdin", "true"))
        if tty:
            q.append(("tty", "true"))
        fs = self._open_stream(
            f"/api/v1/namespaces/{namespace}/pods/{name}/exec?"
            + urlencode(q))
        if fs is None:
            return 1
        err = err or self.out

        def pump_stdin():
            if stdin is not None:
                fs.send(streams.STDIN, stdin)
            elif interactive:
                while True:
                    data = sys.stdin.buffer.read(4096)
                    if not data:
                        break
                    fs.send(streams.STDIN, data)
            fs.send_close(streams.STDIN)

        import threading
        threading.Thread(target=pump_stdin, daemon=True).start()
        code = 1
        try:
            while True:
                frame = fs.recv()
                if frame is None:
                    break
                ch, payload = frame
                if ch == streams.STDOUT:
                    self.out.write(payload.decode(errors="replace"))
                elif ch == streams.STDERR:
                    err.write(payload.decode(errors="replace"))
                elif ch == streams.ERROR:
                    code, msg = streams.parse_exit_status(payload)
                    if code and msg:
                        err.write(msg + "\n")
                    break
        finally:
            fs.close()
        return code

    def _exec_capture(self, name: str, namespace: str, command: list[str],
                      container: str | None = None,
                      stdin: bytes | None = None) -> tuple[int, bytes, str]:
        """exec with BINARY stdout capture (cp needs the tar bytes
        undecoded): returns (exit_code, stdout_bytes, stderr_text)."""
        from urllib.parse import urlencode

        from ..kubelet import streams
        q = [("command", c) for c in command] + [("stdout", "true"),
                                                 ("stderr", "true")]
        if container:
            q.append(("container", container))
        if stdin is not None:
            q.append(("stdin", "true"))
        fs = self._open_stream(
            f"/api/v1/namespaces/{namespace}/pods/{name}/exec?"
            + urlencode(q))
        if fs is None:
            return 1, b"", "stream open failed"
        if stdin is not None:
            # stay under the stream frame cap (streams.MAX_FRAME)
            step = 1 << 20
            for at in range(0, len(stdin), step):
                fs.send(streams.STDIN, stdin[at:at + step])
            fs.send_close(streams.STDIN)
        code, out, err = 0, [], []
        try:
            while True:
                frame = fs.recv()
                if frame is None:
                    break
                ch, payload = frame
                if ch == streams.STDOUT:
                    out.append(payload)
                elif ch == streams.STDERR:
                    err.append(payload.decode(errors="replace"))
                elif ch == streams.ERROR:
                    code, msg = streams.parse_exit_status(payload)
                    if msg:
                        err.append(msg)
                    break
        finally:
            fs.close()
        return code, b"".join(out), "".join(err)

    @staticmethod
    def _parse_cp_spec(spec: str, default_ns: str):
        """[[namespace/]pod:]path -> (pod or None, namespace, path)
        (kubectl/pkg/cmd/cp/cp.go extractFileSpec)."""
        before, sep, after = spec.partition(":")
        if not sep:
            return None, default_ns, spec
        ns, slash, pod = before.partition("/")
        if slash:
            return pod, ns, after
        return before, default_ns, after

    def cp(self, src: str, dst: str, namespace: str,
           container: str | None = None) -> int:
        """kubectl cp: tar over the exec tunnel, both directions
        (kubectl/pkg/cmd/cp/cp.go copyToPod/copyFromPod)."""
        import io as pyio
        import os
        import posixpath
        import tarfile

        s_pod, s_ns, s_path = self._parse_cp_spec(src, namespace)
        d_pod, d_ns, d_path = self._parse_cp_spec(dst, namespace)
        if (s_pod is None) == (d_pod is None):
            self.out.write("Error: one of src/dest must be a remote spec "
                           "(pod:path) and the other local\n")
            return 1
        if s_pod is not None:
            # pod -> local: tar cf - <path> in the container, untar here
            code, data, err = self._exec_capture(
                s_pod, s_ns, ["tar", "cf", "-", s_path], container)
            if code != 0:
                self.out.write(f"Error: {err or 'tar failed'}\n")
                return 1
            try:
                with tarfile.open(fileobj=pyio.BytesIO(data)) as tf:
                    members = [m for m in tf.getmembers() if m.isfile()]
                    for m in members:
                        if len(members) == 1 and not os.path.isdir(dst):
                            target = dst
                        else:
                            rel = posixpath.relpath(
                                "/" + m.name, posixpath.dirname(
                                    "/" + s_path.lstrip("/")) or "/")
                            target = os.path.join(dst, rel)
                        os.makedirs(os.path.dirname(target) or ".",
                                    exist_ok=True)
                        with open(target, "wb") as f:
                            f.write(tf.extractfile(m).read())
            except tarfile.TarError as e:
                self.out.write(f"Error: bad tar stream: {e}\n")
                return 1
            return 0
        # local -> pod: tar the local file(s), tar xmf - -C <dir> there
        if not os.path.exists(src):
            self.out.write(f"Error: {src}: no such file\n")
            return 1
        if d_path.endswith("/"):
            # trailing slash == directory destination: keep the source name
            dest_dir = posixpath.normpath("/" + d_path.lstrip("/"))
            dest_name = os.path.basename(src.rstrip("/"))
        else:
            dest_dir = posixpath.dirname("/" + d_path.lstrip("/")) or "/"
            dest_name = posixpath.basename(d_path) or os.path.basename(
                src.rstrip("/"))
        buf = pyio.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            if os.path.isdir(src):
                for root, _dirs, names in os.walk(src):
                    for nm in names:
                        full = os.path.join(root, nm)
                        rel = os.path.join(
                            dest_name, os.path.relpath(full, src))
                        ti = tarfile.TarInfo(rel)
                        ti.size = os.path.getsize(full)
                        with open(full, "rb") as f:
                            tf.addfile(ti, f)
            else:
                ti = tarfile.TarInfo(dest_name)
                ti.size = os.path.getsize(src)
                with open(src, "rb") as f:
                    tf.addfile(ti, f)
        code, _, err = self._exec_capture(
            d_pod, d_ns, ["tar", "xmf", "-", "-C", dest_dir], container,
            stdin=buf.getvalue())
        if code != 0:
            self.out.write(f"Error: {err or 'tar failed'}\n")
            return 1
        return 0

    def proxy(self, port: int = 8001, ready=None, once: bool = False) -> int:
        """kubectl proxy: local plain-HTTP listener forwarding every
        request to the apiserver with this kubectl's credentials attached
        (kubectl/pkg/cmd/proxy)."""
        import http.server

        http_client = self._http_client()
        if http_client is None:
            self.out.write("Error: this command needs --server\n")
            return 1
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # Nagle + delayed ACK cost ~40ms per request on loopback
            disable_nagle_algorithm = True
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _forward(self):
                from ..client.http_client import make_connection
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else None
                conn = make_connection(
                    http_client.host, http_client.port,
                    getattr(http_client, "_ssl_context", None))
                try:
                    headers = dict(http_client._headers)
                    ct = self.headers.get("Content-Type")
                    if ct:
                        headers["Content-Type"] = ct
                    conn.request(self.command, self.path, body=body,
                                 headers=headers)
                    resp = conn.getresponse()
                    self.send_response(resp.status)
                    self.send_header("Content-Type",
                                     resp.getheader("Content-Type",
                                                    "application/json"))
                    length = resp.getheader("Content-Length")
                    if length is not None:
                        self.send_header("Content-Length", length)
                        self.end_headers()
                        remaining = int(length)
                        while remaining > 0:
                            chunk = resp.read(min(remaining, 65536))
                            if not chunk:
                                break
                            self.wfile.write(chunk)
                            remaining -= len(chunk)
                        return
                    # unknown length (watch streams): re-chunk through,
                    # flushing each piece so events arrive live
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    while True:
                        chunk = resp.read1(65536)
                        if not chunk:
                            break
                        self.wfile.write(b"%x\r\n%s\r\n"
                                         % (len(chunk), chunk))
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                except OSError as e:
                    try:
                        self.send_error(502, str(e))
                    except OSError:  # pragma: no cover - client gone
                        pass
                finally:
                    conn.close()

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _forward

        server = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                 Handler)
        bound = server.server_address[1]
        self.out.write(f"Starting to serve on 127.0.0.1:{bound}\n")
        if ready is not None:
            ready(bound)
        try:
            if once:
                server.handle_request()
            else:
                server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            server.server_close()
        return 0

    def attach(self, name: str, namespace: str,
               container: str | None = None, stdin: bytes | None = None,
               tty: bool = False) -> int:
        """kubectl attach: same stream contract as exec, no command —
        the kubelet attaches to the running entrypoint's console."""
        from urllib.parse import urlencode

        from ..kubelet import streams
        q = [("stdout", "true"), ("stderr", "true")]
        if container:
            q.append(("container", container))
        if stdin is not None:
            q.append(("stdin", "true"))
        if tty:
            q.append(("tty", "true"))
        fs = self._open_stream(
            f"/api/v1/namespaces/{namespace}/pods/{name}/attach?"
            + urlencode(q))
        if fs is None:
            return 1
        if stdin is not None:
            fs.send(streams.STDIN, stdin)
        code = 0
        try:
            while True:
                frame = fs.recv()
                if frame is None:
                    break
                ch, payload = frame
                if ch == streams.STDOUT:
                    self.out.write(payload.decode(errors="replace"))
                elif ch == streams.ERROR:
                    code, _ = streams.parse_exit_status(payload)
                    break
        finally:
            fs.close()
        return code

    def port_forward(self, name: str, namespace: str, mapping: str,
                     ready=None, once: bool = False) -> int:
        """kubectl port-forward pod [local:]remote — a real local
        listener; each accepted connection gets its own upgraded stream
        to the kubelet (the per-connection stream pair of
        kubectl/pkg/cmd/portforward)."""
        import socket as socketlib
        import threading

        from ..kubelet import streams
        local_s, _, remote_s = mapping.partition(":")
        if not remote_s:
            local_s, remote_s = "", local_s
        try:
            remote = int(remote_s)
            local = int(local_s) if local_s else 0
        except ValueError:
            self.out.write(f"Error: bad port mapping {mapping!r}\n")
            return 1
        http = self._http_client()
        if http is None:
            self.out.write("Error: this command needs --server\n")
            return 1
        listener = socketlib.socket()
        listener.setsockopt(socketlib.SOL_SOCKET,
                            socketlib.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", local))
        listener.listen(8)
        bound = listener.getsockname()[1]
        self.out.write(f"Forwarding from 127.0.0.1:{bound} -> {remote}\n")
        if ready is not None:
            ready(bound)

        path = (f"/api/v1/namespaces/{namespace}/pods/{name}/portforward"
                f"?port={remote}")

        def serve(conn: socketlib.socket) -> None:
            try:
                fs = streams.open_upgrade(
                    http.host, http.port, path, headers=http._headers,
                    ssl_context=getattr(http, "_ssl_context", None))
            except streams.StreamError as e:
                conn.close()
                self.out.write(f"Error: {e}\n")
                return
            done = threading.Event()

            def local_to_stream():
                try:
                    while True:
                        data = conn.recv(65536)
                        if not data:
                            break
                        fs.send(streams.PF_DATA, data)
                    fs.send_close(streams.PF_DATA)
                except OSError:
                    pass

            t = threading.Thread(target=local_to_stream, daemon=True)
            t.start()
            try:
                while True:
                    frame = fs.recv()
                    if frame is None:
                        break
                    ch, payload = frame
                    if ch == streams.PF_DATA:
                        conn.sendall(payload)
                    elif ch == streams.PF_ERROR:
                        self.out.write(
                            f"Error: {payload.decode(errors='replace')}\n")
                        break
            except OSError:
                pass
            finally:
                done.set()
                fs.close()
                # shutdown first: close() alone leaves the FIN unsent
                # while local_to_stream sits in recv on this socket
                try:
                    conn.shutdown(socketlib.SHUT_RDWR)
                except OSError:
                    pass
                conn.close()

        try:
            while True:
                conn, _ = listener.accept()
                if once:
                    serve(conn)
                    return 0
                threading.Thread(target=serve, args=(conn,),
                                 daemon=True).start()
        except KeyboardInterrupt:
            return 0
        finally:
            listener.close()

    # -- rollout / label / annotate / patch / wait ------------------------

    def rollout(self, action: str, resource: str, name: str,
                namespace: str, timeout: float = 60.0) -> int:
        """rollout status|restart|undo (kubectl/pkg/cmd/rollout)."""
        resource = self.resolve(resource)
        if action == "status":
            deadline = time.time() + timeout
            while time.time() < deadline:
                try:
                    o = self.client.get(resource, namespace, name)
                except kv.NotFoundError as e:
                    self.out.write(f"Error: {e}\n")
                    return 1
                spec = o.get("spec") or {}
                st = o.get("status") or {}
                want = spec.get("replicas", 1)
                ready = st.get("readyReplicas", 0)
                # deployments must also have rolled all replicas onto the
                # NEW template (rollout_status.go DeploymentStatusViewer:
                # updatedReplicas == replicas), else a restart reports
                # success while old-RS pods still serve
                updated = st.get("updatedReplicas", ready)
                gen_ok = st.get("observedGeneration", 0) >= \
                    o["metadata"].get("generation", 0)
                if gen_ok and ready >= want and updated >= want:
                    self.out.write(f'{resource} "{name}" successfully '
                                   f"rolled out\n")
                    return 0
                time.sleep(0.1)
            self.out.write(f"error: rollout status timed out for {name}\n")
            return 1
        if action == "restart":
            # restartedAt annotation on the pod template forces new pods
            def patch(o):
                tmpl = o.setdefault("spec", {}).setdefault("template", {})
                ann = tmpl.setdefault("metadata", {}).setdefault(
                    "annotations", {})
                ann["kubectl.kubernetes.io/restartedAt"] = str(time.time())
                o["metadata"]["generation"] = \
                    o["metadata"].get("generation", 0) + 1
                return o
            try:
                self.client.guaranteed_update(resource, namespace, name,
                                              patch)
            except kv.NotFoundError as e:
                self.out.write(f"Error: {e}\n")
                return 1
            self.out.write(f"{resource}/{name} restarted\n")
            return 0
        if action == "undo":
            # roll back to the previous revision's template, read from the
            # deployment's retained old ReplicaSets (rollout history —
            # kubectl/pkg/cmd/rollout + deployment/rollback.go semantics)
            from ..controllers.deployment import HASH_LABEL, template_hash
            try:
                o = self.client.get(resource, namespace, name)
            except kv.NotFoundError as e:
                self.out.write(f"Error: {e}\n")
                return 1
            cur_hash = template_hash((o.get("spec") or {}).get("template")
                                     or {})
            rses, _ = self.client.list("replicasets", namespace)
            old = [rs for rs in rses
                   if any(r.get("uid") == meta.uid(o)
                          for r in meta.owner_references(rs))
                   and meta.labels(rs).get(HASH_LABEL) != cur_hash]
            if not old:
                self.out.write("error: no rollout history\n")
                return 1
            prev_rs = max(old, key=meta.creation_timestamp)
            prev_tmpl = ((prev_rs.get("spec") or {}).get("template") or {})
            # drop the controller-stamped hash label from the restored
            # template so re-hashing is stable
            tmpl = json.loads(json.dumps(prev_tmpl))
            (tmpl.get("metadata") or {}).get("labels", {}).pop(
                HASH_LABEL, None)

            def revert(obj):
                obj["spec"]["template"] = tmpl
                obj["metadata"]["generation"] = \
                    obj["metadata"].get("generation", 0) + 1
                return obj
            try:
                self.client.guaranteed_update(resource, namespace, name,
                                              revert)
            except kv.NotFoundError as e:
                self.out.write(f"Error: {e}\n")
                return 1
            self.out.write(f"{resource}/{name} rolled back\n")
            return 0
        self.out.write(f"error: unknown rollout action {action}\n")
        return 1

    def _update_any_scope(self, resource: str, name: str, namespace: str,
                          patch) -> None:
        """guaranteed_update with the same namespaced-then-cluster-scoped
        fallback get/describe/delete use (raises NotFoundError if both
        miss)."""
        try:
            self.client.guaranteed_update(resource, namespace, name, patch)
        except kv.NotFoundError:
            self.client.guaranteed_update(resource, "", name, patch)

    def _kv_patch(self, resource: str, name: str, namespace: str,
                  pairs: list[str], field: str) -> int:
        """Shared label/annotate implementation: k=v sets, k- removes."""
        resource = self.resolve(resource)

        def patch(o):
            target = o["metadata"].setdefault(field, {})
            for pair in pairs:
                if pair.endswith("-") and "=" not in pair:
                    target.pop(pair[:-1], None)
                else:
                    k, _, v = pair.partition("=")
                    target[k] = v
            return o
        try:
            self._update_any_scope(resource, name, namespace, patch)
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        verb = "labeled" if field == "labels" else "annotated"
        self.out.write(f"{resource}/{name} {verb}\n")
        return 0

    def auth_can_i(self, verb: str, resource: str, namespace: str) -> int:
        """kubectl auth can-i — a SelfSubjectAccessReview for the caller's
        own identity (kubectl pkg/cmd/auth/cani.go)."""
        if hasattr(self.client, "store"):
            # in-process client: no authn/authz seam to consult
            self.out.write("yes (in-process client, no authorizer)\n")
            return 0
        review = {"apiVersion": "authorization.k8s.io/v1",
                  "kind": "SelfSubjectAccessReview",
                  "spec": {"resourceAttributes": {
                      "verb": verb,
                      "resource": self.resolve(resource),
                      "namespace": namespace or ""}}}
        try:
            out = self.client.create("selfsubjectaccessreviews", review)
        except kv.StoreError as e:
            self.out.write(f"error: {e}\n")
            return 1
        allowed = (out.get("status") or {}).get("allowed", False)
        self.out.write("yes\n" if allowed else "no\n")
        return 0 if allowed else 1

    def diff(self, path: str, namespace: str) -> int:
        """kubectl diff — live object vs what a server-side apply of the
        manifest would produce (computed with the SAME merge the server
        runs, apiserver/managedfields.py), as a unified diff."""
        import difflib

        from ..apiserver import managedfields as mf
        rc = 0
        for obj in self._load_manifests(path):
            res = self._kind_to_resource(obj.get("kind", ""))
            if not res:
                self.out.write(f"error: unknown kind {obj.get('kind')}\n")
                return 2
            obj.setdefault("metadata", {})
            if res not in CLUSTER_SCOPED_RESOURCES:
                # real kubectl never stamps a namespace onto a
                # cluster-scoped object (it would fork the storage key)
                obj["metadata"].setdefault("namespace", namespace)
            ns, nm = meta.namespace(obj), meta.name(obj)
            try:
                live = self.client.get(res, ns, nm)
            except kv.NotFoundError:
                live = None
            try:
                merged = mf.apply_merge(live, obj, "kubectl", force=True)
            except Exception as e:  # noqa: BLE001
                self.out.write(f"error: {e}\n")
                return 2

            def clean(o):
                if o is None:
                    return []
                o = meta.deep_copy(o)
                md = o.get("metadata") or {}
                for k in ("managedFields", "resourceVersion", "uid",
                          "creationTimestamp"):
                    md.pop(k, None)
                return yaml.safe_dump(o, sort_keys=True).splitlines(
                    keepends=True)

            delta = list(difflib.unified_diff(
                clean(live), clean(merged),
                fromfile=f"live/{res}/{nm}", tofile=f"merged/{res}/{nm}"))
            if delta:
                rc = 1  # differences found (kubectl diff exit contract)
                self.out.writelines(delta)
        return rc

    def edit(self, resource: str, name: str, namespace: str,
             editor: str | None = None) -> int:
        """kubectl edit (kubectl/pkg/cmd/edit): dump the live object to
        a temp YAML file, run $EDITOR on it, PUT the result back.  The
        live resourceVersion rides along so a concurrent change
        surfaces as a 409 instead of a silent overwrite."""
        import os
        import subprocess
        import tempfile
        resource = self.resolve(resource)
        try:
            obj = self.client.get(resource, namespace, name)
        except kv.NotFoundError:
            try:
                obj = self.client.get(resource, "", name)
            except kv.NotFoundError as e:
                self.out.write(f"Error: {e}\n")
                return 1
        editor = editor or os.environ.get("EDITOR") or "vi"
        with tempfile.NamedTemporaryFile(
                "w+", suffix=".yaml", prefix=f"kubectl-edit-{name}-",
                delete=False) as f:
            yaml.safe_dump(obj, f, sort_keys=False)
            path = f.name
        try:
            proc = subprocess.run([*editor.split(), path])
            if proc.returncode != 0:
                self.out.write("Edit cancelled (editor exited "
                               f"{proc.returncode})\n")
                return 1
            try:
                with open(path) as f:
                    edited = yaml.safe_load(f)
            except yaml.YAMLError as e:
                self.out.write(f"Error: edited file is not valid YAML: "
                               f"{e}\n")
                return 1
        finally:
            os.unlink(path)
        if edited is None:
            # an emptied buffer is the standard "abort the edit" gesture
            self.out.write("Edit cancelled (empty file)\n")
            return 0
        if edited == obj:
            self.out.write(f"{resource}/{name} unchanged\n")
            return 0
        try:
            self.client.update(resource, edited)
        except kv.ConflictError as e:
            self.out.write(f"Error: {e}\nhint: the object changed while "
                           "you edited; re-run kubectl edit\n")
            return 1
        except kv.StoreError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        self.out.write(f"{resource}/{name} edited\n")
        return 0

    def debug(self, name: str, namespace: str, image: str,
              copy_to: str | None = None,
              command: list[str] | None = None) -> int:
        """kubectl debug (kubectl/pkg/cmd/debug): pod-copy mode — clone
        the target pod, add a debug container, strip probes so the copy
        stays alive for inspection."""
        try:
            pod = self.client.get(PODS, namespace, name)
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        copy_name = copy_to or f"{name}-debug"
        dbg = meta.deep_copy(pod)
        # the copy deliberately carries NO workload labels: the source's
        # selector labels would get it adopted by its ReplicaSet (which
        # then kills a surplus replica) and routed to by Services whose
        # probes were just stripped — real kubectl omits them the same way
        dbg["metadata"] = {
            "name": copy_name, "namespace": namespace,
            "labels": {"debug.kubernetes.io/source": name}}
        dbg.pop("status", None)
        spec = dbg.setdefault("spec", {})
        spec.pop("nodeName", None)  # reschedule the copy
        taken = set()
        for c in spec.get("containers") or ():
            c.pop("livenessProbe", None)
            c.pop("readinessProbe", None)
            taken.add(c.get("name"))
        dbg_name = "debugger"
        n = 1
        while dbg_name in taken:
            dbg_name = f"debugger-{n}"
            n += 1
        spec.setdefault("containers", []).append({
            "name": dbg_name, "image": image,
            "command": command or ["sh"], "stdin": True, "tty": True})
        try:
            self.client.create(PODS, dbg)
        except kv.AlreadyExistsError:
            self.out.write(f"Error: pod {copy_name!r} already exists\n")
            return 1
        self.out.write(f"pod/{copy_name} created (debug copy of {name} "
                       f"with container 'debugger')\n")
        return 0

    def taint(self, node: str, spec: str) -> int:
        """kubectl taint nodes <node> key[=value]:Effect | key-"""
        if spec.endswith("-"):
            key = spec[:-1]

            def strip(o):
                taints = (o.get("spec") or {}).get("taints") or []
                o.setdefault("spec", {})["taints"] = [
                    t for t in taints if t.get("key") != key]
                return o
            try:
                self.client.guaranteed_update("nodes", "", node, strip)
            except kv.NotFoundError:
                self.out.write(f"error: node {node!r} not found\n")
                return 1
            self.out.write(f"node/{node} untainted\n")
            return 0
        kv_part, _, effect = spec.rpartition(":")
        if not effect or not kv_part:
            self.out.write("error: taint must be key[=value]:Effect "
                           "or key-\n")
            return 1
        key, _, value = kv_part.partition("=")
        taint = {"key": key, "value": value, "effect": effect}

        def add(o):
            taints = o.setdefault("spec", {}).setdefault("taints", [])
            taints[:] = [t for t in taints if t.get("key") != key]
            taints.append(taint)
            return o
        try:
            self.client.guaranteed_update("nodes", "", node, add)
        except kv.NotFoundError:
            self.out.write(f"error: node {node!r} not found\n")
            return 1
        self.out.write(f"node/{node} tainted\n")
        return 0

    def label(self, resource, name, namespace, pairs) -> int:
        return self._kv_patch(resource, name, namespace, pairs, "labels")

    def annotate(self, resource, name, namespace, pairs) -> int:
        return self._kv_patch(resource, name, namespace, pairs, "annotations")

    def patch(self, resource: str, name: str, namespace: str,
              patch_json: str) -> int:
        """kubectl patch — RFC 7386 merge patch, the same implementation
        the apiserver's merge-patch content type uses (apiserver/patch.py)
        so CLI and API semantics can't drift."""
        from ..apiserver.patch import json_merge_patch
        resource = self.resolve(resource)
        try:
            delta = json.loads(patch_json)
        except json.JSONDecodeError as e:
            self.out.write(f"error: invalid patch: {e}\n")
            return 1
        try:
            self._update_any_scope(resource, name, namespace,
                                   lambda o: json_merge_patch(o, delta))
        except kv.NotFoundError as e:
            self.out.write(f"Error: {e}\n")
            return 1
        self.out.write(f"{resource}/{name} patched\n")
        return 0

    def wait(self, resource: str, name: str, namespace: str,
             condition: str, timeout: float = 30.0) -> int:
        """kubectl wait --for=condition=<Type> | --for=delete."""
        resource = self.resolve(resource)
        want_delete = condition == "delete"
        cond_name = (condition.partition("=")[2]
                     if condition.startswith("condition=") else "")
        if not want_delete and not cond_name:
            self.out.write("error: --for must be condition=<Type> or "
                           "delete\n")
            return 1
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                o = self.client.get(resource, namespace, name)
            except kv.NotFoundError:
                try:  # cluster-scoped fallback (same as get/describe)
                    o = self.client.get(resource, "", name)
                except kv.NotFoundError:
                    if want_delete:
                        self.out.write(f"{resource}/{name} deleted\n")
                        return 0
                    time.sleep(0.1)
                    continue
            if not want_delete:
                for c in (o.get("status") or {}).get("conditions") or ():
                    if (c.get("type", "").lower() == cond_name.lower()
                            and c.get("status") == "True"):
                        self.out.write(
                            f"{resource}/{name} condition met\n")
                        return 0
            time.sleep(0.1)
        self.out.write(f"error: timed out waiting for {resource}/{name}\n")
        return 1


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="kubectl", description=__doc__)
    ap.add_argument("--server", "-s", default="http://127.0.0.1:8080")
    ap.add_argument("--token", default=None)
    ap.add_argument("--kubeconfig", default=None,
                    help="kubeconfig file (kubeadm output): endpoint + "
                         "pinned CA + client-cert or token credentials")
    ap.add_argument("--namespace", "-n", default="default")
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("get")
    g.add_argument("resource")
    g.add_argument("name", nargs="?")
    g.add_argument("-o", "--output",
                   help="json|yaml|wide|name|jsonpath=TEMPLATE")
    g.add_argument("-l", "--selector", default=None)
    g.add_argument("--field-selector", dest="field_selector",
                   default=None)
    g.add_argument("-A", "--all-namespaces", action="store_true",
                   dest="all_namespaces")
    d = sub.add_parser("describe")
    d.add_argument("resource")
    d.add_argument("name")
    for verb in ("create", "apply"):
        c = sub.add_parser(verb)
        c.add_argument("-f", "--filename", default=None)
        if verb == "apply":
            c.add_argument("-k", "--kustomize", default=None,
                           help="kustomization directory")
            c.add_argument("--force-conflicts", action="store_true")
        else:
            c.add_argument("gen", nargs=argparse.REMAINDER,
                           help="generator: deployment|configmap|"
                                "secret generic|namespace|service "
                                "clusterip|nodeport|job NAME [flags]")
    ks = sub.add_parser("kustomize")
    ks.add_argument("dir")
    dl = sub.add_parser("delete")
    dl.add_argument("resource", nargs="?")
    dl.add_argument("name", nargs="?")
    dl.add_argument("-f", "--filename", default=None)
    dl.add_argument("-l", "--selector", default=None)
    sc = sub.add_parser("scale")
    sc.add_argument("resource")
    sc.add_argument("name")
    sc.add_argument("--replicas", type=int, required=True)
    for verb in ("cordon", "uncordon", "drain"):
        cn = sub.add_parser(verb)
        cn.add_argument("node")
    tp = sub.add_parser("top")
    tp.add_argument("what", choices=["nodes", "pods", "pod", "node"])
    tp.add_argument("-A", "--all-namespaces", action="store_true",
                    dest="all_namespaces")
    lg = sub.add_parser("logs")
    lg.add_argument("name")
    lg.add_argument("-c", "--container", default=None)
    lg.add_argument("-f", "--follow", action="store_true")
    lg.add_argument("--tail", type=int, default=None)
    ex = sub.add_parser("exec")
    ex.add_argument("name")
    ex.add_argument("-c", "--container", default=None)
    ex.add_argument("-i", "--stdin", action="store_true", dest="interactive")
    ex.add_argument("-t", "--tty", action="store_true")
    ex.add_argument("command", nargs="*", help="-- COMMAND [args...]")
    at = sub.add_parser("attach")
    at.add_argument("name")
    at.add_argument("-c", "--container", default=None)
    at.add_argument("-t", "--tty", action="store_true")
    pf = sub.add_parser("port-forward")
    pf.add_argument("name")
    pf.add_argument("mapping", help="[local:]remote")
    ro = sub.add_parser("rollout")
    ro.add_argument("action", choices=["status", "restart", "undo"])
    ro.add_argument("resource")
    ro.add_argument("name")
    ro.add_argument("--timeout", type=float, default=60.0)
    for verb in ("label", "annotate"):
        lb = sub.add_parser(verb)
        lb.add_argument("resource")
        lb.add_argument("name")
        lb.add_argument("pairs", nargs="+", help="k=v to set, k- to remove")
    pt = sub.add_parser("patch")
    pt.add_argument("resource")
    pt.add_argument("name")
    pt.add_argument("-p", "--patch", required=True, help="JSON merge patch")
    wt = sub.add_parser("wait")
    wt.add_argument("resource")
    wt.add_argument("name")
    wt.add_argument("--for", dest="condition", required=True,
                    help="condition=<Type> or delete")
    wt.add_argument("--timeout", type=float, default=30.0)
    au = sub.add_parser("auth")
    au.add_argument("subcmd", choices=["can-i"])
    au.add_argument("verb")
    au.add_argument("resource")
    df = sub.add_parser("diff")
    df.add_argument("-f", "--filename", required=True)
    ed = sub.add_parser("edit")
    ed.add_argument("resource")
    ed.add_argument("name")
    db = sub.add_parser("debug")
    db.add_argument("name")
    db.add_argument("--image", default="busybox")
    db.add_argument("--copy-to", dest="copy_to", default=None)
    tn = sub.add_parser("taint")
    tn.add_argument("resource", choices=["nodes", "node"])
    tn.add_argument("node")
    tn.add_argument("spec", help="key[=value]:Effect to add, key- to remove")
    sub.add_parser("version")
    sub.add_parser("api-versions")
    ar = sub.add_parser("api-resources")
    ar.add_argument("--namespaced", default=None,
                    choices=["true", "false"])
    xp = sub.add_parser("explain")
    xp.add_argument("dotted", help="resource[.field.path]")
    ep = sub.add_parser("expose")
    ep.add_argument("resource")
    ep.add_argument("name")
    ep.add_argument("--port", type=int, required=True)
    ep.add_argument("--target-port", dest="target_port", type=int,
                    default=None)
    ep.add_argument("--name", dest="svc_name", default=None)
    ep.add_argument("--type", dest="svc_type", default="ClusterIP")
    ep.add_argument("--protocol", default="TCP")
    asc = sub.add_parser("autoscale")
    asc.add_argument("resource")
    asc.add_argument("name")
    asc.add_argument("--min", dest="min_replicas", type=int, required=True)
    asc.add_argument("--max", dest="max_replicas", type=int, required=True)
    asc.add_argument("--cpu-percent", dest="cpu_percent", type=int,
                     default=None)
    st = sub.add_parser("set")
    st.add_argument("what", choices=["image", "env"])
    st.add_argument("resource")
    st.add_argument("name")
    st.add_argument("kvs", nargs="+",
                    help="image: CONTAINER=IMAGE...; env: KEY=VALUE...")
    cp = sub.add_parser("cp")
    cp.add_argument("src", help="local path or [[ns/]pod:]path")
    cp.add_argument("dst", help="local path or [[ns/]pod:]path")
    cp.add_argument("-c", "--container", default=None)
    px = sub.add_parser("proxy")
    px.add_argument("--port", type=int, default=8001)
    return ap


def run(argv: list[str] | None = None, client: Client | None = None,
        out=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # split at the first bare "--": flags like -i must bind to kubectl
    # even after the pod name (argparse REMAINDER would swallow them)
    tail: list[str] = []
    if "--" in argv:
        cut = argv.index("--")
        argv, tail = argv[:cut], argv[cut + 1:]
    args = build_parser().parse_args(argv)
    out = out or sys.stdout
    if client is None:
        if args.kubeconfig:
            client = HTTPClient.from_kubeconfig(args.kubeconfig)
        else:
            client = HTTPClient.from_url(args.server, args.token)
    k = Kubectl(client, out)
    if args.cmd == "get":
        return k.get(args.resource, args.name, args.namespace, args.output,
                     selector=args.selector,
                     all_namespaces=args.all_namespaces,
                     field_selector=args.field_selector)
    if args.cmd == "describe":
        return k.describe(args.resource, args.name, args.namespace)
    if args.cmd == "create":
        if args.filename:
            return k.create(args.filename, args.namespace)
        if args.gen:
            # `tail` is everything after a bare `--`: the job command
            return k.create_generated(args.gen[0], args.gen[1:],
                                      args.namespace,
                                      command=tail or None)
        out.write("error: create needs -f FILE or a generator "
                  "(deployment, configmap, secret generic, namespace, "
                  "service clusterip|nodeport, job)\n")
        return 1
    if args.cmd == "apply":
        if args.kustomize and args.filename:
            out.write("error: cannot specify -f and -k together\n")
            return 1
        if args.kustomize:
            return k.apply_kustomize(args.kustomize, args.namespace,
                                     force=args.force_conflicts)
        if not args.filename:
            out.write("error: apply needs -f FILE or -k DIR\n")
            return 1
        return k.apply(args.filename, args.namespace,
                       force=args.force_conflicts)
    if args.cmd == "kustomize":
        return k.kustomize(args.dir)
    if args.cmd == "delete":
        if args.filename:
            return k.delete_file(args.filename, args.namespace)
        if args.selector is not None:
            if not args.resource:
                out.write("error: delete -l needs a resource\n")
                return 1
            return k.delete_selector(args.resource, args.selector,
                                     args.namespace)
        if not args.resource or not args.name:
            out.write("error: delete needs RESOURCE NAME, -f FILE, "
                      "or RESOURCE -l SELECTOR\n")
            return 1
        return k.delete(args.resource, args.name, args.namespace)
    if args.cmd == "scale":
        return k.scale(args.resource, args.name, args.namespace, args.replicas)
    if args.cmd == "cordon":
        return k.cordon(args.node, True)
    if args.cmd == "uncordon":
        return k.cordon(args.node, False)
    if args.cmd == "drain":
        return k.drain(args.node)
    if args.cmd == "top":
        if args.what in ("pods", "pod"):
            return k.top_pods(args.namespace,
                              all_namespaces=args.all_namespaces)
        return k.top_nodes()
    if args.cmd == "logs":
        return k.logs(args.name, args.namespace, container=args.container,
                      follow=args.follow, tail=args.tail)
    if args.cmd == "exec":
        command = args.command or tail
        if not command:
            out.write("Error: exec needs -- COMMAND\n")
            return 1
        return k.exec(args.name, args.namespace, command,
                      container=args.container,
                      interactive=args.interactive, tty=args.tty)
    if args.cmd == "attach":
        return k.attach(args.name, args.namespace,
                        container=args.container, tty=args.tty)
    if args.cmd == "port-forward":
        return k.port_forward(args.name, args.namespace, args.mapping)
    if args.cmd == "rollout":
        return k.rollout(args.action, args.resource, args.name,
                         args.namespace, args.timeout)
    if args.cmd == "label":
        return k.label(args.resource, args.name, args.namespace, args.pairs)
    if args.cmd == "annotate":
        return k.annotate(args.resource, args.name, args.namespace,
                          args.pairs)
    if args.cmd == "patch":
        return k.patch(args.resource, args.name, args.namespace, args.patch)
    if args.cmd == "wait":
        return k.wait(args.resource, args.name, args.namespace,
                      args.condition, args.timeout)
    if args.cmd == "auth":
        return k.auth_can_i(args.verb, args.resource, args.namespace)
    if args.cmd == "diff":
        return k.diff(args.filename, args.namespace)
    if args.cmd == "edit":
        return k.edit(args.resource, args.name, args.namespace)
    if args.cmd == "debug":
        return k.debug(args.name, args.namespace, args.image,
                       copy_to=args.copy_to, command=tail or None)
    if args.cmd == "taint":
        return k.taint(args.node, args.spec)
    if args.cmd == "api-versions":
        return k.api_versions()
    if args.cmd == "api-resources":
        ns = None if args.namespaced is None else args.namespaced == "true"
        return k.api_resources(namespaced=ns)
    if args.cmd == "explain":
        return k.explain(args.dotted)
    if args.cmd == "expose":
        return k.expose(args.resource, args.name, args.namespace,
                        args.port, args.target_port, args.svc_name,
                        args.svc_type, args.protocol)
    if args.cmd == "autoscale":
        return k.autoscale(args.resource, args.name, args.namespace,
                           args.min_replicas, args.max_replicas,
                           args.cpu_percent)
    if args.cmd == "set":
        return k.set_cmd(args.what, args.resource, args.name,
                         args.namespace, args.kvs)
    if args.cmd == "cp":
        return k.cp(args.src, args.dst, args.namespace,
                    container=args.container)
    if args.cmd == "proxy":
        return k.proxy(args.port)
    if args.cmd == "version":
        out.write(f"kubectl-tpu v{__version__}\n")
        return 0
    return 1


def main() -> None:  # console entry
    sys.exit(run())


if __name__ == "__main__":
    main()
