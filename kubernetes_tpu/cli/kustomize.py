"""kustomize build — the load-bearing subset.

Reference: sigs.k8s.io/kustomize as vendored by kubectl
(staging/src/k8s.io/kubectl/pkg/cmd/apply with -k; cli-runtime's
resource builder).  Supported kustomization.yaml fields, applied in
kustomize's documented transform order:

  resources:            files (multi-doc YAML) and directories (each a
                        sub-kustomization, recursively built)
  bases:                legacy alias for directory resources
  patchesStrategicMerge: per-file strategic-merge patches matched by
                        (apiVersion-group, kind, name, namespace)
  patches:              [{path|patch, target:{kind,name,...}}] with
                        strategic-merge payloads
  images:               [{name, newName, newTag}] container image rewrites
  namePrefix/nameSuffix: metadata.name decoration
  namespace:            set on namespaced objects
  commonLabels:         metadata.labels + the workload selector/template
                        labels (kustomize updates selectors too)
  commonAnnotations:    metadata.annotations

Everything else (generators, replacements, vars, components) is out of
scope; unknown fields raise so a kustomization is never silently
half-applied.
"""

from __future__ import annotations

import os

import yaml

from ..apiserver import patch as patchlib

_SUPPORTED = {
    "apiVersion", "kind", "metadata",  # kustomization self-description
    "resources", "bases", "patchesStrategicMerge", "patches", "images",
    "namePrefix", "nameSuffix", "namespace", "commonLabels",
    "commonAnnotations",
}

_STRATEGIC = "application/strategic-merge-patch+json"


class KustomizeError(Exception):
    pass


def _load_kustomization(directory: str) -> dict:
    for name in ("kustomization.yaml", "kustomization.yml",
                 "Kustomization"):
        path = os.path.join(directory, name)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    doc = yaml.safe_load(f) or {}
            except OSError as e:
                raise KustomizeError(
                    f"cannot read {path!r}: {e}") from e
            except yaml.YAMLError as e:
                raise KustomizeError(
                    f"bad YAML in {path!r}: {e}") from e
            unknown = set(doc) - _SUPPORTED
            if unknown:
                raise KustomizeError(
                    f"{path}: unsupported kustomization fields "
                    f"{sorted(unknown)}")
            return doc
    raise KustomizeError(f"no kustomization.yaml in {directory!r}")


def _load_docs(path: str) -> list[dict]:
    try:
        with open(path) as f:
            return [d for d in yaml.safe_load_all(f) if d]
    except OSError as e:
        raise KustomizeError(f"cannot read {path!r}: {e}") from e
    except yaml.YAMLError as e:
        raise KustomizeError(f"bad YAML in {path!r}: {e}") from e


def _split_image(ref: str) -> tuple[str, str, str]:
    """image ref -> (name, tag, digest).  The tag colon is the one AFTER
    the last slash (registries carry ports: myreg.io:5000/web:1.0)."""
    base, _, digest = ref.partition("@")
    slash = base.rfind("/")
    colon = base.rfind(":")
    if colon > slash:
        return base[:colon], base[colon + 1:], digest
    return base, "", digest


def _gk(obj: dict) -> tuple[str, str]:
    group = (obj.get("apiVersion") or "").partition("/")[0] \
        if "/" in (obj.get("apiVersion") or "") else ""
    return group, obj.get("kind") or ""


def _matches(obj: dict, target: dict) -> bool:
    md = obj.get("metadata") or {}
    og, ok = _gk(obj)
    if target.get("kind") and target["kind"] != ok:
        return False
    if target.get("group") is not None and target.get("group") != og:
        return False
    if target.get("name") and target["name"] != md.get("name"):
        return False
    if target.get("namespace") \
            and target["namespace"] != md.get("namespace"):
        return False
    return True


def build(directory: str, _seen: frozenset = frozenset()) -> list[dict]:
    """Resolve a kustomization directory to its final object list.

    Kinds not in the builtin scope table are treated as NAMESPACED for
    the namespace transform — kustomize's own default when it has no
    openapi data for a type."""
    real = os.path.realpath(directory)
    if real in _seen:
        raise KustomizeError(
            f"kustomization cycle detected at {directory!r}")
    _seen = _seen | {real}
    k = _load_kustomization(directory)
    objs: list[dict] = []
    for entry in list(k.get("resources") or ()) + list(k.get("bases")
                                                       or ()):
        path = os.path.join(directory, entry)
        if os.path.isdir(path):
            objs.extend(build(path, _seen))
        elif os.path.exists(path):
            objs.extend(_load_docs(path))
        else:
            raise KustomizeError(f"resource {entry!r} not found under "
                                 f"{directory!r}")

    # -- strategic merge patches -----------------------------------------
    patch_docs: list[tuple[dict, dict | None]] = []  # (patch, target|None)
    for entry in k.get("patchesStrategicMerge") or ():
        for p in _load_docs(os.path.join(directory, entry)):
            patch_docs.append((p, None))
    for entry in k.get("patches") or ():
        if "path" in entry:
            loaded = _load_docs(os.path.join(directory, entry["path"]))
        else:
            loaded = [d for d in yaml.safe_load_all(
                entry.get("patch") or "") if d]
        for p in loaded:
            patch_docs.append((p, entry.get("target")))
    for p, target in patch_docs:
        tgt = target or {
            "kind": p.get("kind"),
            "name": (p.get("metadata") or {}).get("name"),
            "namespace": (p.get("metadata") or {}).get("namespace"),
        }
        hit = False
        for i, obj in enumerate(objs):
            if _matches(obj, tgt):
                objs[i] = patchlib.apply_patch(_STRATEGIC, obj, p)
                hit = True
        if not hit:
            raise KustomizeError(
                f"patch targets no resource: {tgt}")

    # -- image rewrites ---------------------------------------------------
    for img in k.get("images") or ():
        name = img.get("name", "")
        for obj in objs:
            spec = ((obj.get("spec") or {}).get("template")
                    or {}).get("spec") or obj.get("spec") or {}
            for c in (list(spec.get("containers") or ())
                      + list(spec.get("initContainers") or ())):
                base, tag, digest = _split_image(c.get("image") or "")
                if base != name:
                    continue
                new_base = img.get("newName", base)
                if "newTag" in img:
                    c["image"] = f"{new_base}:{img['newTag']}"
                elif digest:
                    c["image"] = f"{new_base}@{digest}"
                else:
                    c["image"] = (f"{new_base}:{tag}" if tag
                                  else new_base)

    # -- name/namespace/labels/annotations -------------------------------
    prefix = k.get("namePrefix") or ""
    suffix = k.get("nameSuffix") or ""
    namespace = k.get("namespace")
    labels = k.get("commonLabels") or {}
    annotations = k.get("commonAnnotations") or {}
    from ..client.clientset import CLUSTER_SCOPED_RESOURCES
    from .kubectl import KIND_TO_RESOURCE
    for obj in objs:
        md = obj.setdefault("metadata", {})
        if prefix or suffix:
            md["name"] = f"{prefix}{md.get('name', '')}{suffix}"
        if namespace:
            res = KIND_TO_RESOURCE.get(obj.get("kind") or "")
            if res not in CLUSTER_SCOPED_RESOURCES:
                md["namespace"] = namespace
        if labels:
            md.setdefault("labels", {}).update(labels)
            spec = obj.get("spec") or {}
            sel = spec.get("selector")
            if isinstance(sel, dict) and "matchLabels" in sel:
                sel["matchLabels"].update(labels)
            elif isinstance(sel, dict) and obj.get("kind") == "Service":
                sel.update(labels)
            tmpl_md = (spec.get("template") or {}).get("metadata")
            if isinstance(tmpl_md, dict):
                tmpl_md.setdefault("labels", {}).update(labels)
        if annotations:
            md.setdefault("annotations", {}).update(annotations)
    return objs
