"""Client machinery: clientset, informers, workqueues, leader election."""

from .clientset import Client, LocalClient  # noqa: F401
from .informer import Informer, SharedInformerFactory  # noqa: F401
from .workqueue import DelayingQueue, RateLimiter, RateLimitingQueue, WorkQueue  # noqa: F401
