"""Client interface to the API.

Reference: staging/src/k8s.io/client-go (typed clientset).  Two
implementations share one interface:

  LocalClient - in-process, directly over store.MemoryStore.  This is what
      integration tests and scheduler_perf use (the reference does the same:
      its integration harness runs an in-process apiserver,
      test/integration/framework/test_server.go:62).
  HTTPClient  - over the REST apiserver (apiserver/server.py), for
      multi-process deployments.  (added by apiserver module)

All methods deal in JSON-shaped dict objects (api.meta.Obj).
"""

from __future__ import annotations

from typing import Callable

from ..api import meta
from ..api.meta import Obj
from ..store import kv
from ..store.kv import MemoryStore, NotFoundError, Watch

# Canonical resource names (plural, lowercase — like REST paths).
PODS = "pods"
NODES = "nodes"
SERVICES = "services"
ENDPOINTS = "endpoints"
EVENTS = "events"
LEASES = "leases"
REPLICASETS = "replicasets"
DEPLOYMENTS = "deployments"
JOBS = "jobs"
NAMESPACES = "namespaces"
CONFIGMAPS = "configmaps"
SECRETS = "secrets"
PVCS = "persistentvolumeclaims"
PVS = "persistentvolumes"
PDBS = "poddisruptionbudgets"
PODGROUPS = "podgroups"
STATEFULSETS = "statefulsets"
DAEMONSETS = "daemonsets"
REPLICATIONCONTROLLERS = "replicationcontrollers"
PRIORITYCLASSES = "priorityclasses"
STORAGECLASSES = "storageclasses"
CSINODES = "csinodes"
CRONJOBS = "cronjobs"
RESOURCEQUOTAS = "resourcequotas"
SERVICEACCOUNTS = "serviceaccounts"
LIMITRANGES = "limitranges"
HPAS = "horizontalpodautoscalers"
ENDPOINTSLICES = "endpointslices"
CSRS = "certificatesigningrequests"
VOLUMEATTACHMENTS = "volumeattachments"
ROLES = "roles"
CLUSTERROLES = "clusterroles"
ROLEBINDINGS = "rolebindings"
CLUSTERROLEBINDINGS = "clusterrolebindings"

# the ONE cluster-scoped set: REST routing (apiserver/server.py) and client
# path building (http_client.py) both key off it — divergence routes writes
# to the wrong key (tests/test_verify_static.py guards the sharing)
CLUSTER_SCOPED_RESOURCES = frozenset({
    NODES, PVS, NAMESPACES, PRIORITYCLASSES, STORAGECLASSES, CSINODES,
    CSRS, VOLUMEATTACHMENTS, CLUSTERROLES, CLUSTERROLEBINDINGS,
    "apiservices", "customresourcedefinitions", "storageversions",
    "flowschemas", "prioritylevelconfigurations",
})


class Client:
    """Abstract client; see LocalClient."""

    def create(self, resource: str, obj: Obj) -> Obj:
        raise NotImplementedError

    def get(self, resource: str, namespace: str, name: str) -> Obj:
        raise NotImplementedError

    def update(self, resource: str, obj: Obj) -> Obj:
        raise NotImplementedError

    def guaranteed_update(self, resource: str, namespace: str, name: str,
                          fn: Callable[[Obj], Obj]) -> Obj:
        raise NotImplementedError

    def delete(self, resource: str, namespace: str, name: str) -> Obj:
        raise NotImplementedError

    def list(self, resource: str, namespace: str | None = None) -> tuple[list[Obj], int]:
        raise NotImplementedError

    def watch(self, resource: str, since_rv: int | None = None) -> Watch:
        raise NotImplementedError

    # -- conveniences used across the tree --------------------------------

    def bind(self, pod: Obj, node_name: str,
             expect_rv: int | None = None) -> Obj:
        """POST pods/{name}/binding equivalent: set spec.nodeName atomically.

        Reference: pkg/registry/core/pod/storage BindingREST — fails if the
        pod is already bound (the scheduler relies on this for correctness
        under races).  With N scheduler instances committing optimistically
        against one store, losing that race raises a structured BindConflict
        (kv.py) naming the current owner; the optional expect_rv tightens
        the precondition to compare-and-bind on the pod's resourceVersion.
        """
        ns, nm = meta.namespace(pod), meta.name(pod)
        if not node_name:
            # an empty nodeName stores as "unbound" to every reader: the
            # pod would be silently lost.  Same guard as the store's
            # bulk bind_many — refuse loudly so the caller requeues.
            raise kv.StoreError(f"bind {ns}/{nm}: empty node name refused")

        def apply(cur: Obj) -> Obj:
            if cur["spec"].get("nodeName"):
                bound_to = cur["spec"]["nodeName"]
                raise kv.BindConflict(
                    f"pod {ns}/{nm} is already bound to {bound_to!r}",
                    key=f"{ns}/{nm}" if ns else nm,
                    current_node=bound_to, wanted_node=node_name)
            if expect_rv is not None and \
                    cur["metadata"].get("resourceVersion") != expect_rv:
                raise kv.BindConflict(
                    f"pod {ns}/{nm} moved past resourceVersion "
                    f"{expect_rv!r}",
                    key=f"{ns}/{nm}" if ns else nm,
                    current_node=None, wanted_node=node_name)
            cur["spec"]["nodeName"] = node_name
            conds = cur.setdefault("status", {}).setdefault("conditions", [])
            conds.append({"type": "PodScheduled", "status": "True"})
            return cur

        return self.guaranteed_update(PODS, ns, nm, apply)

    def bind_many(self, bindings: list[tuple]
                  ) -> list[tuple[Obj | None, Exception | None]]:
        """Bulk bind: (namespace, name, node_name[, expect_rv]) entries,
        per-entry results.  Generic clients fall back to per-pod bind();
        LocalClient uses the store's transactional multi-bind.  Entries that
        lose the optimistic bind race come back as kv.BindConflict."""
        out: list[tuple[Obj | None, Exception | None]] = []
        for entry in bindings:
            ns, nm, node = entry[0], entry[1], entry[2]
            expect_rv = entry[3] if len(entry) > 3 else None
            try:
                out.append((self.bind({"metadata": {"namespace": ns,
                                                    "name": nm}}, node,
                                      expect_rv=expect_rv), None))
            except Exception as e:
                # per-entry, and not just StoreError: one pod's transport
                # blip must not abort the rest of the batch — the caller
                # classifies each entry on its own
                out.append((None, e))
        return out

    def update_status(self, resource: str, obj: Obj) -> Obj:
        """Status-subresource write: merge .status only."""
        status = obj.get("status") or {}

        def apply(cur: Obj) -> Obj:
            cur["status"] = status
            return cur

        return self.guaranteed_update(resource, meta.namespace(obj), meta.name(obj), apply)

    # EventAggregator semantics (client-go record/events_cache.go:60-120):
    # more than maxEvents "similar" events (same involved kind/ns/reason/
    # type — everything but name+message) inside maxIntervalInSeconds get
    # collapsed into ONE aggregate record whose count bumps.  At bench
    # scale this is also the perf contract: 50k binds emit 50k Scheduled
    # events that collapse into aggregate count bumps, not 50k writes.
    EVENT_AGGREGATE_MAX = 10          # record.defaultAggregateMaxEvents
    EVENT_AGGREGATE_WINDOW = 600.0    # defaultAggregateIntervalInSeconds

    def create_event(self, regarding: Obj, reason: str, message: str,
                     type_: str = "Normal") -> None:
        """Fire-and-forget Event via a background broadcaster thread
        (reference: record.EventBroadcaster buffers and writes async; events
        must never sit on the scheduling/binding critical path).  Overflow
        drops events, like the broadcaster's bounded queue.  Only a compact
        tuple is built here — dict construction, correlation and the store
        write all happen on the broadcaster thread."""
        md = regarding["metadata"]
        self._event_sink((regarding.get("kind"), md.get("namespace", ""),
                          md["name"], md.get("uid", ""), reason, message,
                          type_))

    def create_event_burst(self, items: list[tuple[Obj, str, str]]) -> None:
        """create_event for a whole batch with ONE queue round:
        (regarding, reason, message) triples.  The bulk bind tail emits
        one Scheduled event per pod — per-pod create_event costs ~7µs of
        binder-thread time each at 100k-tier scale; the burst enqueue is
        one deque.extend."""
        recs = []
        for regarding, reason, message in items:
            md = regarding["metadata"]
            recs.append((regarding.get("kind"), md.get("namespace", ""),
                         md["name"], md.get("uid", ""), reason, message,
                         "Normal"))
        self._event_sink_many(recs)

    def _event_sink_many(self, recs: list[tuple]) -> None:
        if not recs:
            return
        q = getattr(self, "_event_queue", None)
        if q is None:
            self._event_sink(recs[0])  # starts the broadcaster thread
            recs = recs[1:]
            q = self._event_queue
            if q is None or not recs:  # racing close()
                return
        room = self.EVENT_BUF_MAX - len(q)
        if room > 0:
            q.extend(recs[:room])
            # racing producers can overshoot the cap by up to one burst
            # each (room was read non-atomically); shed our own newest
            # records so the bounded-queue contract holds
            while len(q) > self.EVENT_BUF_MAX:
                try:
                    q.pop()
                except IndexError:  # pragma: no cover - consumer drained
                    break
            wake = self._event_wake
            if not wake.is_set():
                wake.set()

    _event_init_lock = __import__("threading").Lock()

    EVENT_BUF_MAX = 50_000

    def _event_sink(self, rec: tuple) -> None:
        import threading
        from collections import deque
        q = getattr(self, "_event_queue", None)
        if q is None:
            with Client._event_init_lock:
                q = getattr(self, "_event_queue", None)
                if q is None:
                    q = deque()
                    self._event_wake = threading.Event()
                    t = threading.Thread(target=self._event_drain_loop,
                                         args=(q, self._event_wake),
                                         name="event-broadcaster",
                                         daemon=True)
                    t.start()
                    self._event_thread = t
                    self._event_queue = q
        # lock-free enqueue: deque.append is GIL-atomic (a queue.Queue's
        # mutex cost ~1µs per event on the binder hot path); overflow
        # drops, like the reference broadcaster's bounded channel.  The
        # LOCAL q: close() may null _event_queue concurrently (an event
        # racing close lands in the drained queue = dropped).
        if len(q) < self.EVENT_BUF_MAX:
            q.append(rec)
            wake = self._event_wake
            if not wake.is_set():
                wake.set()

    def _event_drain_loop(self, q, wake) -> None:
        """Broadcaster thread: drain compact records in chunks, correlate
        (aggregate beyond the similar-events threshold), flush one bulk
        create for individual events + one count-bump write per aggregate
        key per chunk."""
        import time as _t
        # key -> [count, window_start, aggregate_name_or_None]
        corr: dict[tuple, list] = {}
        while True:
            if not q:
                wake.wait(0.2)
            wake.clear()
            chunk = []
            try:
                while len(chunk) < 4096:
                    chunk.append(q.popleft())
            except IndexError:
                pass
            if not chunk:
                continue
            stop = None in chunk  # close() sentinel
            now = _t.time()
            fresh: list[Obj] = []
            bumps: dict[tuple, tuple[int, tuple]] = {}  # key -> (delta, rec)
            for rec in chunk:
                if rec is None:
                    continue
                kind, ns, nm, uid, reason, message, type_ = rec
                key = (kind, ns, reason, type_)
                st = corr.get(key)
                if st is None or now - st[1] > self.EVENT_AGGREGATE_WINDOW:
                    st = corr[key] = [0, now, None]
                st[0] += 1
                if st[0] <= self.EVENT_AGGREGATE_MAX:
                    fresh.append(self._build_event(rec, now))
                else:
                    delta, _ = bumps.get(key, (0, rec))
                    bumps[key] = (delta + 1, rec)
            try:
                if fresh:
                    self.create_events(fresh)
                for key, (delta, rec) in bumps.items():
                    self._bump_aggregate(corr[key], key, rec, delta, now)
            except kv.StoreError:
                pass
            if stop:
                return

    @staticmethod
    def _build_event(rec: tuple, now: float) -> Obj:
        kind, ns, nm, uid, reason, message, type_ = rec
        return {"apiVersion": "v1", "kind": "Event",
                "metadata": {"name": f"{nm}.{int(now * 1e6):x}",
                             "namespace": ns or "default"},
                "type": type_, "reason": reason, "message": message,
                "count": 1,
                "involvedObject": {"kind": kind, "namespace": ns,
                                   "name": nm, "uid": uid}}

    def _bump_aggregate(self, st: list, key: tuple, rec: tuple, delta: int,
                        now: float) -> None:
        """Write/bump the aggregate record for a similar-events key
        (events_cache.go EventAggregate: '(combined from similar events)')."""
        kind, ns, nm, uid, reason, message, type_ = rec
        ns_eff = ns or "default"
        if st[2] is None:
            agg = self._build_event(rec, now)
            agg["message"] = f"(combined from similar events): {message}"
            agg["count"] = self.EVENT_AGGREGATE_MAX + delta
            st[2] = agg["metadata"]["name"]
            try:
                self.create(EVENTS, agg)
                return
            except kv.StoreError:
                st[2] = None
                return
        name = st[2]

        def bump(cur: Obj) -> Obj:
            cur["count"] = int(cur.get("count", 1)) + delta
            cur["message"] = f"(combined from similar events): {message}"
            return cur

        try:
            self.guaranteed_update(EVENTS, ns_eff, name, bump)
        except kv.StoreError:
            st[2] = None  # aggregate evaporated (GC'd): recreate next time

    def close(self) -> None:
        """Stop the event-broadcaster thread, flushing buffered events
        (joins the drain thread so the flush completes before return;
        the broadcaster restarts lazily if events are recorded later)."""
        q = getattr(self, "_event_queue", None)
        t = getattr(self, "_event_thread", None)
        if q is None:
            return
        self._event_queue = None  # next create_event restarts the thread
        q.append(None)  # close sentinel
        self._event_wake.set()
        if t is not None:
            t.join(timeout=5.0)

    def create_events(self, events: list[Obj]) -> None:
        """Write a burst of Events. Generic clients write one by one;
        LocalClient uses the store's bulk create."""
        for ev in events:
            try:
                self.create(EVENTS, ev)
            except kv.StoreError:
                pass


class LocalClient(Client):
    """Direct in-process client over a MemoryStore."""

    def __init__(self, store: MemoryStore):
        self.store = store

    def create(self, resource: str, obj: Obj) -> Obj:
        return self.store.create(resource, obj)

    def get(self, resource: str, namespace: str, name: str) -> Obj:
        return self.store.get(resource, namespace, name)

    def update(self, resource: str, obj: Obj) -> Obj:
        return self.store.update(resource, obj)

    def guaranteed_update(self, resource: str, namespace: str, name: str,
                          fn: Callable[[Obj], Obj]) -> Obj:
        return self.store.guaranteed_update(resource, namespace, name, fn)

    def delete(self, resource: str, namespace: str, name: str,
               propagation_policy: str | None = None) -> Obj:
        fin = meta.propagation_finalizer(propagation_policy)
        if fin is not None:
            def park(cur, fin=fin):
                fins = cur["metadata"].setdefault("finalizers", [])
                if fin not in fins:
                    fins.append(fin)
                return cur
            self.store.guaranteed_update(resource, namespace, name, park)
        return self.store.delete(resource, namespace, name)

    def apply(self, resource: str, obj: Obj, field_manager: str,
              force: bool = False) -> Obj:
        """Server-side apply (managedfields.py semantics, in process)."""
        from ..apiserver import managedfields as mf
        ns, nm = obj["metadata"].get("namespace", ""), obj["metadata"]["name"]

        def merge(cur):
            new = mf.apply_merge(cur, obj, field_manager, force=force)
            new["metadata"]["resourceVersion"] = \
                cur["metadata"].get("resourceVersion")
            return new

        for _ in range(2):
            try:
                return self.store.guaranteed_update(resource, ns, nm, merge)
            except NotFoundError:
                pass
            try:
                return self.store.create(
                    resource, mf.apply_merge(None, obj, field_manager))
            except kv.AlreadyExistsError:
                continue  # lost the create race: merge with the winner
        return self.store.guaranteed_update(resource, ns, nm, merge)

    def list(self, resource: str, namespace: str | None = None) -> tuple[list[Obj], int]:
        return self.store.list(resource, namespace)

    def watch(self, resource: str, since_rv: int | None = None) -> Watch:
        return self.store.watch(resource, since_rv)

    def bind_many(self, bindings: list[tuple[str, str, str]]
                  ) -> list[tuple[Obj | None, Exception | None]]:
        return self.store.bind_many(PODS, bindings)

    def create_events(self, events: list[Obj]) -> None:
        # broadcaster-owned objects, never touched after the flush:
        # ownership transfer, no inbound copy
        self.store.create_many(EVENTS, events, copy=False)

    def create_bulk(self, resource: str, objs: list[Obj]) -> None:
        """Bulk object submission (perf-harness transport analog of the
        reference's 5000-QPS burst client, util.go:92).  Ownership
        transfer: the caller must not touch the objects after this call
        (copy=False).  Raises on the first error — harness payloads are
        generated, not user input."""
        for obj, err in self.store.create_many(resource, objs, copy=False):
            if err is not None:
                raise err
