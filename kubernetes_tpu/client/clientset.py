"""Client interface to the API.

Reference: staging/src/k8s.io/client-go (typed clientset).  Two
implementations share one interface:

  LocalClient - in-process, directly over store.MemoryStore.  This is what
      integration tests and scheduler_perf use (the reference does the same:
      its integration harness runs an in-process apiserver,
      test/integration/framework/test_server.go:62).
  HTTPClient  - over the REST apiserver (apiserver/server.py), for
      multi-process deployments.  (added by apiserver module)

All methods deal in JSON-shaped dict objects (api.meta.Obj).
"""

from __future__ import annotations

from typing import Callable

from ..api import meta
from ..api.meta import Obj
from ..store import kv
from ..store.kv import MemoryStore, NotFoundError, Watch

# Canonical resource names (plural, lowercase — like REST paths).
PODS = "pods"
NODES = "nodes"
SERVICES = "services"
ENDPOINTS = "endpoints"
EVENTS = "events"
LEASES = "leases"
REPLICASETS = "replicasets"
DEPLOYMENTS = "deployments"
JOBS = "jobs"
NAMESPACES = "namespaces"
CONFIGMAPS = "configmaps"
SECRETS = "secrets"
PVCS = "persistentvolumeclaims"
PVS = "persistentvolumes"
PDBS = "poddisruptionbudgets"
PODGROUPS = "podgroups"
STATEFULSETS = "statefulsets"
DAEMONSETS = "daemonsets"
REPLICATIONCONTROLLERS = "replicationcontrollers"
PRIORITYCLASSES = "priorityclasses"
STORAGECLASSES = "storageclasses"
CSINODES = "csinodes"
CRONJOBS = "cronjobs"
RESOURCEQUOTAS = "resourcequotas"
SERVICEACCOUNTS = "serviceaccounts"
LIMITRANGES = "limitranges"
HPAS = "horizontalpodautoscalers"
ENDPOINTSLICES = "endpointslices"
CSRS = "certificatesigningrequests"
VOLUMEATTACHMENTS = "volumeattachments"
ROLES = "roles"
CLUSTERROLES = "clusterroles"
ROLEBINDINGS = "rolebindings"
CLUSTERROLEBINDINGS = "clusterrolebindings"

# the ONE cluster-scoped set: REST routing (apiserver/server.py) and client
# path building (http_client.py) both key off it — divergence routes writes
# to the wrong key (tests/test_verify_static.py guards the sharing)
CLUSTER_SCOPED_RESOURCES = frozenset({
    NODES, PVS, NAMESPACES, PRIORITYCLASSES, STORAGECLASSES, CSINODES,
    CSRS, VOLUMEATTACHMENTS, CLUSTERROLES, CLUSTERROLEBINDINGS,
    "apiservices", "customresourcedefinitions", "storageversions",
})


class Client:
    """Abstract client; see LocalClient."""

    def create(self, resource: str, obj: Obj) -> Obj:
        raise NotImplementedError

    def get(self, resource: str, namespace: str, name: str) -> Obj:
        raise NotImplementedError

    def update(self, resource: str, obj: Obj) -> Obj:
        raise NotImplementedError

    def guaranteed_update(self, resource: str, namespace: str, name: str,
                          fn: Callable[[Obj], Obj]) -> Obj:
        raise NotImplementedError

    def delete(self, resource: str, namespace: str, name: str) -> Obj:
        raise NotImplementedError

    def list(self, resource: str, namespace: str | None = None) -> tuple[list[Obj], int]:
        raise NotImplementedError

    def watch(self, resource: str, since_rv: int | None = None) -> Watch:
        raise NotImplementedError

    # -- conveniences used across the tree --------------------------------

    def bind(self, pod: Obj, node_name: str) -> Obj:
        """POST pods/{name}/binding equivalent: set spec.nodeName atomically.

        Reference: pkg/registry/core/pod/storage BindingREST — fails if the
        pod is already bound (the scheduler relies on this for correctness
        under races).
        """
        ns, nm = meta.namespace(pod), meta.name(pod)

        def apply(cur: Obj) -> Obj:
            if cur["spec"].get("nodeName"):
                raise kv.ConflictError(
                    f"pod {ns}/{nm} is already bound to {cur['spec']['nodeName']!r}")
            cur["spec"]["nodeName"] = node_name
            conds = cur.setdefault("status", {}).setdefault("conditions", [])
            conds.append({"type": "PodScheduled", "status": "True"})
            return cur

        return self.guaranteed_update(PODS, ns, nm, apply)

    def bind_many(self, bindings: list[tuple[str, str, str]]
                  ) -> list[tuple[Obj | None, Exception | None]]:
        """Bulk bind: (namespace, name, node_name) triples, per-entry
        results.  Generic clients fall back to per-pod bind(); LocalClient
        uses the store's transactional multi-bind."""
        out: list[tuple[Obj | None, Exception | None]] = []
        for ns, nm, node in bindings:
            try:
                out.append((self.bind({"metadata": {"namespace": ns,
                                                    "name": nm}}, node), None))
            except kv.StoreError as e:
                out.append((None, e))
        return out

    def update_status(self, resource: str, obj: Obj) -> Obj:
        """Status-subresource write: merge .status only."""
        status = obj.get("status") or {}

        def apply(cur: Obj) -> Obj:
            cur["status"] = status
            return cur

        return self.guaranteed_update(resource, meta.namespace(obj), meta.name(obj), apply)

    def create_event(self, regarding: Obj, reason: str, message: str,
                     type_: str = "Normal") -> None:
        """Fire-and-forget Event via a background broadcaster thread
        (reference: record.EventBroadcaster buffers and writes async; events
        must never sit on the scheduling/binding critical path).  Overflow
        drops events, like the broadcaster's bounded queue."""
        import time as _t
        md = regarding["metadata"]
        ns = md.get("namespace", "")
        nm = md["name"]
        ev = {"apiVersion": "v1", "kind": "Event",
              "metadata": {"name": f"{nm}.{int(_t.time() * 1e6):x}",
                           "namespace": ns or "default"},
              "type": type_, "reason": reason, "message": message,
              "involvedObject": {"kind": regarding.get("kind"),
                                 "namespace": ns, "name": nm,
                                 "uid": md.get("uid", "")}}
        self._event_sink(ev)

    _event_init_lock = __import__("threading").Lock()

    def _event_sink(self, ev: Obj) -> None:
        import queue as _q
        import threading
        q = getattr(self, "_event_queue", None)
        if q is None:
            with Client._event_init_lock:
                q = getattr(self, "_event_queue", None)
                if q is None:
                    q = _q.Queue(maxsize=10_000)

                    def drain() -> None:
                        # drain in chunks: one write per buffered burst keeps
                        # event traffic off the scheduler's GIL/lock budget
                        while True:
                            chunk = [q.get()]
                            try:
                                while len(chunk) < 512:
                                    chunk.append(q.get_nowait())
                            except _q.Empty:
                                pass
                            stop = None in chunk  # close() sentinel
                            chunk = [e for e in chunk if e is not None]
                            try:
                                if chunk:
                                    self.create_events(chunk)
                            except kv.StoreError:
                                pass
                            if stop:
                                return

                    t = threading.Thread(target=drain,
                                         name="event-broadcaster",
                                         daemon=True)
                    t.start()
                    self._event_thread = t
                    self._event_queue = q
        try:
            # the LOCAL q: close() may null _event_queue concurrently (an
            # event racing close lands in the drained queue = dropped,
            # bounded-broadcaster semantics, never an AttributeError)
            q.put_nowait(ev)
        except _q.Full:
            pass  # queue full: drop (bounded broadcaster semantics)

    def close(self) -> None:
        """Stop the event-broadcaster thread, flushing buffered events
        (joins the drain thread so the flush completes before return;
        the broadcaster restarts lazily if events are recorded later)."""
        q = getattr(self, "_event_queue", None)
        t = getattr(self, "_event_thread", None)
        if q is None:
            return
        self._event_queue = None  # next create_event restarts the thread
        try:
            q.put(None, timeout=1.0)
        except Exception:  # noqa: BLE001 - full queue: drop the flush
            return
        if t is not None:
            t.join(timeout=5.0)

    def create_events(self, events: list[Obj]) -> None:
        """Write a burst of Events. Generic clients write one by one;
        LocalClient uses the store's bulk create."""
        for ev in events:
            try:
                self.create(EVENTS, ev)
            except kv.StoreError:
                pass


class LocalClient(Client):
    """Direct in-process client over a MemoryStore."""

    def __init__(self, store: MemoryStore):
        self.store = store

    def create(self, resource: str, obj: Obj) -> Obj:
        return self.store.create(resource, obj)

    def get(self, resource: str, namespace: str, name: str) -> Obj:
        return self.store.get(resource, namespace, name)

    def update(self, resource: str, obj: Obj) -> Obj:
        return self.store.update(resource, obj)

    def guaranteed_update(self, resource: str, namespace: str, name: str,
                          fn: Callable[[Obj], Obj]) -> Obj:
        return self.store.guaranteed_update(resource, namespace, name, fn)

    def delete(self, resource: str, namespace: str, name: str,
               propagation_policy: str | None = None) -> Obj:
        fin = meta.propagation_finalizer(propagation_policy)
        if fin is not None:
            def park(cur, fin=fin):
                fins = cur["metadata"].setdefault("finalizers", [])
                if fin not in fins:
                    fins.append(fin)
                return cur
            self.store.guaranteed_update(resource, namespace, name, park)
        return self.store.delete(resource, namespace, name)

    def apply(self, resource: str, obj: Obj, field_manager: str,
              force: bool = False) -> Obj:
        """Server-side apply (managedfields.py semantics, in process)."""
        from ..apiserver import managedfields as mf
        ns, nm = obj["metadata"].get("namespace", ""), obj["metadata"]["name"]

        def merge(cur):
            new = mf.apply_merge(cur, obj, field_manager, force=force)
            new["metadata"]["resourceVersion"] = \
                cur["metadata"].get("resourceVersion")
            return new

        for _ in range(2):
            try:
                return self.store.guaranteed_update(resource, ns, nm, merge)
            except NotFoundError:
                pass
            try:
                return self.store.create(
                    resource, mf.apply_merge(None, obj, field_manager))
            except kv.AlreadyExistsError:
                continue  # lost the create race: merge with the winner
        return self.store.guaranteed_update(resource, ns, nm, merge)

    def list(self, resource: str, namespace: str | None = None) -> tuple[list[Obj], int]:
        return self.store.list(resource, namespace)

    def watch(self, resource: str, since_rv: int | None = None) -> Watch:
        return self.store.watch(resource, since_rv)

    def bind_many(self, bindings: list[tuple[str, str, str]]
                  ) -> list[tuple[Obj | None, Exception | None]]:
        return self.store.bind_many(PODS, bindings)

    def create_events(self, events: list[Obj]) -> None:
        # broadcaster-owned objects, never touched after the flush:
        # ownership transfer, no inbound copy
        self.store.create_many(EVENTS, events, copy=False)

    def create_bulk(self, resource: str, objs: list[Obj]) -> None:
        """Bulk object submission (perf-harness transport analog of the
        reference's 5000-QPS burst client, util.go:92).  Ownership
        transfer: the caller must not touch the objects after this call
        (copy=False).  Raises on the first error — harness payloads are
        generated, not user input."""
        for obj, err in self.store.create_many(resource, objs, copy=False):
            if err is not None:
                raise err
