"""HTTP client speaking to the REST apiserver.

Reference: staging/src/k8s.io/client-go rest.Client + the watch decoder
(tools/watch). Implements the same Client interface as LocalClient, so
informers/controllers/schedulers run identically in-process or over HTTP.
"""

from __future__ import annotations

import http.client
import json
import threading
from typing import Callable

from ..api import meta
from ..api.meta import Obj
from ..store import kv
from .clientset import CLUSTER_SCOPED_RESOURCES, Client

_ERRORS = {404: kv.NotFoundError, 409: kv.ConflictError, 410: kv.TooOldError}


class HTTPError(kv.StoreError):
    def __init__(self, code: int, message: str):
        self.code = code
        super().__init__(f"HTTP {code}: {message}")


def _bind_conflict_from(body: dict) -> kv.BindConflict:
    """Rehydrate the typed conflict from a 409 Status: the structured
    fields ride the `details` block (apiserver bind_conflict_status) so
    an HTTP scheduler classifies already_bound_same_node/lost_to_peer
    exactly like a LocalClient one."""
    d = body.get("details") or {}
    return kv.BindConflict(body.get("message", ""),
                           key=d.get("name") or "",
                           current_node=d.get("currentNode"),
                           wanted_node=d.get("wantedNode"))


def _raise_for(code: int, body: dict) -> None:
    msg = body.get("message", "")
    if body.get("reason") == "AlreadyExists":
        raise kv.AlreadyExistsError(msg)
    if body.get("reason") == "BindConflict":
        raise _bind_conflict_from(body)
    err = _ERRORS.get(code)
    if err is not None:
        raise err(msg)
    raise HTTPError(code, msg)


def make_connection(host: str, port: int,
                    ssl_context=None) -> http.client.HTTPConnection:
    """The one place HTTP-vs-HTTPS connection choice lives."""
    if ssl_context is not None:
        return http.client.HTTPSConnection(host, port,
                                           context=ssl_context)
    return http.client.HTTPConnection(host, port)


class _ChunkDecoder:
    """Incremental HTTP/1.1 chunked-transfer decoder.

    http.client's own chunked reader is unusable for a live stream: its
    BufferedReader slurps wire bytes into a Python-level buffer that
    select() on the raw socket cannot see, so a delivered event can sit
    invisible until the NEXT write arrives (measured: a 3k-pod burst
    surfaced only when the 5s server heartbeat pushed it out).  Decoding
    the framing ourselves over raw recv() makes readability == select.
    """

    _HEADER, _PAYLOAD, _TRAILER_CRLF, _DONE = range(4)

    def __init__(self, chunked: bool):
        self.chunked = chunked
        self.raw = bytearray()
        self._state = self._HEADER
        self._left = 0  # payload bytes remaining in the current chunk

    def feed(self, data: bytes) -> bytes:
        """Decode more wire bytes; returns the payload bytes produced."""
        if not self.chunked:
            return data
        self.raw += data
        out = bytearray()
        while True:
            if self._state == self._HEADER:
                i = self.raw.find(b"\r\n")
                if i < 0:
                    break
                try:
                    size = int(bytes(self.raw[:i]).split(b";")[0], 16)
                except ValueError:
                    self._state = self._DONE  # corrupt framing: EOF
                    break
                del self.raw[:i + 2]
                if size == 0:
                    self._state = self._DONE
                    break
                self._left = size
                self._state = self._PAYLOAD
            elif self._state == self._PAYLOAD:
                if not self.raw:
                    break
                take = min(self._left, len(self.raw))
                out += self.raw[:take]
                del self.raw[:take]
                self._left -= take
                if self._left == 0:
                    self._state = self._TRAILER_CRLF
            elif self._state == self._TRAILER_CRLF:
                if len(self.raw) < 2:
                    break
                del self.raw[:2]
                self._state = self._HEADER
            else:  # _DONE
                break
        return bytes(out)

    @property
    def done(self) -> bool:
        return self._state == self._DONE


class HTTPWatch:
    """Consumes the newline-delimited JSON watch stream; quacks like kv.Watch.

    Framing is managed explicitly: raw socket recv -> _ChunkDecoder ->
    line buffer.  A poll timeout (select) leaves partial lines/chunks
    intact, and buffered-but-unparsed data can never hide from the
    readability check — see _ChunkDecoder's docstring for why
    http.client's reader cannot be used here."""

    def __init__(self, host: str, port: int, path: str,
                 headers: dict[str, str], ssl_context=None):
        self._conn = make_connection(host, port, ssl_context)
        self._conn.request("GET", path, headers=headers)
        self._resp = self._conn.getresponse()
        if self._resp.status != 200:
            body = json.loads(self._resp.read() or b"{}")
            self._conn.close()
            _raise_for(self._resp.status, body)
        chunked = (self._resp.getheader("Transfer-Encoding", "")
                   .lower() == "chunked")
        self._decoder = _ChunkDecoder(chunked)
        self._buf = bytearray()
        self._stopped = False
        self._lock = threading.Lock()
        self._sock = self._resp.fp.raw._sock \
            if hasattr(self._resp.fp, "raw") else None
        # getresponse()'s header reads may have overshot into the body:
        # drain the BufferedReader's residue without blocking, then stop
        # using it entirely
        if self._sock is not None:
            self._sock.setblocking(False)
            try:
                while True:
                    residue = self._resp.fp.read1(1 << 20)
                    if not residue:
                        break
                    self._buf += self._decoder.feed(residue)
            except (BlockingIOError, OSError):
                pass
            finally:
                self._sock.setblocking(True)

    def _fill(self, timeout: float | None) -> bool:
        """One raw recv into the line buffer. False on timeout/EOF/error
        (EOF/error also set _stopped).

        NEVER sets a socket timeout: SocketIO poisons itself permanently
        after one timed-out read ("cannot read from timed out object").
        Readiness comes from select; the recv itself runs on the
        blocking socket and returns promptly because data is there."""
        if not self._wait_readable(timeout):
            return False  # poll timeout: stream is still alive
        try:
            data = self._sock.recv(65536) if self._sock is not None \
                else self._resp.read1(65536)
        except OSError:
            self._stopped = True
            return False
        if not data:
            self._stopped = True
            return False
        self._buf += self._decoder.feed(data)
        if self._decoder.done:
            self._stopped = True
        return True

    def _wait_readable(self, timeout: float | None) -> bool:
        import select
        sock = self._sock
        if sock is None:  # no raw socket handle: read blocking
            return True
        pending = getattr(sock, "pending", None)  # TLS-layer buffer
        if pending is not None and pending():
            return True
        try:
            return bool(select.select([sock], [], [], timeout)[0])
        except (OSError, ValueError):
            self._stopped = True
            return False

    @staticmethod
    def _parse(line: bytes):
        """WatchEvent, kv.BOOKMARK for heartbeats, or None for junk."""
        try:
            payload = json.loads(line)
        except json.JSONDecodeError:
            return None
        if payload.get("type") == kv.BOOKMARK:
            return kv.BOOKMARK
        return kv.WatchEvent(payload["type"], payload["object"],
                             meta.resource_version(payload["object"]))

    def _next_buffered(self):
        """Next event already in the line buffer: a WatchEvent, the
        kv.BOOKMARK sentinel (heartbeat), or None when the buffer holds
        no complete line.  No socket reads."""
        while True:
            i = self._buf.find(b"\n")
            if i < 0:
                return None
            line = bytes(self._buf[:i + 1])
            del self._buf[:i + 1]
            ev = self._parse(line)
            if ev is None:
                continue  # junk line
            return ev

    def next(self, timeout: float | None = None):
        if self._stopped:
            return None
        while True:
            ev = self._next_buffered()
            if ev is kv.BOOKMARK:
                return None  # heartbeat: caller polls again
            if ev is not None:
                return ev
            if not self._fill(timeout):
                return None

    BATCH_MAX = 4096

    def next_batch(self, timeout: float | None = None):
        """kv.Watch.next_batch parity for bulk informer consumption:
        block for the first event, then drain complete buffered lines
        plus whatever the socket can hand over without blocking — a
        server-side flood arrives as one batch, so the informer's bulk
        handlers take one lock round per burst instead of one per
        event."""
        ev = self.next(timeout)
        if ev is None:
            return []
        batch = [ev]
        while len(batch) < self.BATCH_MAX:
            ev = self._next_buffered()
            if ev is kv.BOOKMARK:
                continue
            if ev is None:
                if self._stopped or not self._wait_readable(0):
                    break
                if not self._fill(0):
                    break
                continue
            batch.append(ev)
        return batch

    def stop(self) -> None:
        with self._lock:
            if not self._stopped:
                self._stopped = True
                try:
                    self._conn.close()
                except OSError:
                    pass

    @property
    def stopped(self) -> bool:
        return self._stopped


class HTTPClient(Client):
    def __init__(self, host: str, port: int, token: str | None = None,
                 cluster_scoped: frozenset[str] = CLUSTER_SCOPED_RESOURCES,
                 tls: dict | None = None):
        """`tls` switches to HTTPS: {"ca_file": pinned server CA or None
        (unverified), "cert_file"/"key_file": optional client cert —
        the X.509 identity the apiserver's client-CA authn reads}."""
        self.host, self.port = host, port
        self._headers = {"Content-Type": "application/json"}
        if token:
            self._headers["Authorization"] = f"Bearer {token}"
        self._cluster_scoped = cluster_scoped
        self._local = threading.local()
        self._ssl_context = None
        if tls is not None:
            import ssl
            if tls.get("ca_file") or tls.get("ca_data"):
                ctx = ssl.create_default_context(
                    cafile=tls.get("ca_file"), cadata=tls.get("ca_data"))
                ctx.check_hostname = False  # pinned CA, IP endpoints
            else:
                ctx = ssl._create_unverified_context()
            if tls.get("cert_file"):
                ctx.load_cert_chain(tls["cert_file"],
                                    keyfile=tls.get("key_file"))
            self._ssl_context = ctx

    @classmethod
    def from_url(cls, url: str, token: str | None = None,
                 tls: dict | None = None) -> "HTTPClient":
        scheme, _, hostport = url.rstrip("/").rpartition("//")
        host, _, port = hostport.partition(":")
        if scheme.startswith("https") and tls is None:
            tls = {}  # unverified TLS — callers pin via tls["ca_file"]
        return cls(host, int(port or (443 if tls is not None else 80)),
                   token, tls=tls)

    @classmethod
    def from_kubeconfig(cls, path: str) -> "HTTPClient":
        """Build a client from a kubeconfig: endpoint + pinned CA +
        either a bearer token or a client cert/key (kubeadm output)."""
        import base64
        import os
        import tempfile

        import yaml
        with open(path) as f:
            doc = yaml.safe_load(f)
        cluster = (doc.get("clusters") or [{}])[0].get("cluster") or {}
        user = (doc.get("users") or [{}])[0].get("user") or {}
        server = cluster.get("server", "http://127.0.0.1:8080")
        tls = None
        tmpdir = None
        if server.startswith("https"):
            tls = {}
            if cluster.get("certificate-authority-data"):
                # CA goes straight into the ssl context — no file
                tls["ca_data"] = base64.b64decode(
                    cluster["certificate-authority-data"]).decode()
            if user.get("client-certificate-data"):
                if not user.get("client-key-data"):
                    raise ValueError(
                        f"kubeconfig {path}: user has "
                        "client-certificate-data but no client-key-data")
                # ssl.load_cert_chain only takes paths: materialize into
                # a TemporaryDirectory whose finalizer removes the key
                # when the client is garbage-collected
                tmpdir = tempfile.TemporaryDirectory(
                    prefix="ktpu-kubeconfig-")
                tls["cert_file"] = os.path.join(tmpdir.name, "client.crt")
                tls["key_file"] = os.path.join(tmpdir.name, "client.key")
                with open(tls["cert_file"], "wb") as f:
                    f.write(base64.b64decode(
                        user["client-certificate-data"]))
                fd = os.open(tls["key_file"],
                             os.O_WRONLY | os.O_CREAT, 0o600)
                with os.fdopen(fd, "wb") as f:
                    f.write(base64.b64decode(user["client-key-data"]))
        client = cls.from_url(server, token=user.get("token"), tls=tls)
        client._tls_tmpdir = tmpdir  # keep the finalizer alive
        return client

    # -- plumbing --------------------------------------------------------

    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._local.conn = make_connection(
                self.host, self.port, self._ssl_context)
        return conn

    def _request(self, method: str, path: str, body: Obj | None = None,
                 content_type: str | None = None) -> dict:
        payload = json.dumps(body) if body is not None else None
        headers = self._headers
        if content_type is not None:
            headers = dict(headers, **{"Content-Type": content_type})
        for attempt in range(2):  # retry once on stale keep-alive conns
            conn = self._conn()
            try:
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                data = json.loads(resp.read() or b"{}")
                break
            except (http.client.HTTPException, OSError):
                self._local.conn = None
                if attempt:
                    raise
        if resp.status >= 400:
            _raise_for(resp.status, data)
        return data

    def _path(self, resource: str, namespace: str | None = None,
              name: str | None = None) -> str:
        if resource in self._cluster_scoped or not namespace:
            p = f"/api/v1/{resource}"
        else:
            p = f"/api/v1/namespaces/{namespace}/{resource}"
        return p + (f"/{name}" if name else "")

    # -- Client ----------------------------------------------------------

    def create(self, resource: str, obj: Obj) -> Obj:
        return self._request("POST", self._path(resource, meta.namespace(obj)),
                             obj)

    def get(self, resource: str, namespace: str, name: str) -> Obj:
        return self._request("GET", self._path(resource, namespace, name))

    def update(self, resource: str, obj: Obj) -> Obj:
        return self._request(
            "PUT", self._path(resource, meta.namespace(obj), meta.name(obj)),
            obj)

    def guaranteed_update(self, resource: str, namespace: str, name: str,
                          fn: Callable[[Obj], Obj], max_retries: int = 16) -> Obj:
        for _ in range(max_retries):
            cur = self.get(resource, namespace, name)
            try:
                return self.update(resource, fn(meta.deep_copy(cur)))
            except kv.ConflictError:
                continue
        raise kv.ConflictError(f"{resource} {namespace}/{name}: too many CAS retries")

    def delete(self, resource: str, namespace: str, name: str,
               propagation_policy: str | None = None) -> Obj:
        path = self._path(resource, namespace, name)
        if propagation_policy:
            path += f"?propagationPolicy={propagation_policy}"
        return self._request("DELETE", path)

    def list(self, resource: str, namespace: str | None = None
             ) -> tuple[list[Obj], int]:
        data = self._request("GET", self._path(resource, namespace))
        return data.get("items", []), int(data["metadata"]["resourceVersion"])

    def watch(self, resource: str, since_rv: int | None = None):
        path = self._path(resource) + "?watch=true"
        if since_rv is not None:
            path += f"&resourceVersion={since_rv}"
        return HTTPWatch(self.host, self.port, path, self._headers,
                         ssl_context=self._ssl_context)

    # -- patch + subresources (endpoints/handlers/patch.go; pod storage) --

    def patch(self, resource: str, namespace: str, name: str, patch_body,
              patch_type: str = "application/strategic-merge-patch+json",
              subresource: str | None = None) -> Obj:
        path = self._path(resource, namespace, name)
        if subresource:
            path += "/" + subresource
        return self._request("PATCH", path, patch_body,
                             content_type=patch_type)

    def apply(self, resource: str, obj: Obj, field_manager: str,
              force: bool = False) -> Obj:
        """Server-side apply: PATCH application/apply-patch+yaml with
        fieldManager/force query params (apply.go sendPatch)."""
        ns = (obj.get("metadata") or {}).get("namespace", "")
        nm = obj["metadata"]["name"]
        path = self._path(resource, ns, nm)
        path += f"?fieldManager={field_manager}"
        if force:
            path += "&force=true"
        return self._request("PATCH", path, obj,
                             content_type="application/apply-patch+yaml")

    def bind(self, pod: Obj, node_name: str,
             expect_rv: int | None = None) -> Obj:
        """POST pods/{name}/binding (DefaultBinder's write).  expect_rv
        rides metadata.resourceVersion as the compare-and-bind
        precondition (scale-out schedulers)."""
        path = self._path("pods", meta.namespace(pod), meta.name(pod)) + "/binding"
        md: Obj = {"name": meta.name(pod)}
        if expect_rv is not None:
            md["resourceVersion"] = expect_rv
        return self._request("POST", path, {
            "kind": "Binding", "apiVersion": "v1",
            "metadata": md,
            "target": {"kind": "Node", "name": node_name}})

    _BULK_ERRORS = {"BindConflict": kv.BindConflict,
                    "Conflict": kv.ConflictError,
                    "NotFound": kv.NotFoundError,
                    "AlreadyExists": kv.AlreadyExistsError}

    def bind_many(self, bindings: list[tuple]
                  ) -> list[tuple[Obj | None, Exception | None]]:
        """Bulk bind through ONE request: POST a BindingList to the
        bindings collection (server: _post_bindings -> kv.bind_many).
        Per-pod fallback when the server predates the bulk verb."""
        items = []
        for entry in bindings:
            ns, nm, node = entry[0], entry[1], entry[2]
            md = {"namespace": ns, "name": nm}
            if len(entry) > 3 and entry[3] is not None:
                md["resourceVersion"] = entry[3]
            items.append({"metadata": md,
                          "target": {"kind": "Node", "name": node}})
        body = {"kind": "BindingList", "apiVersion": "v1", "items": items}
        try:
            resp = self._request("POST", "/api/v1/bindings", body)
        except kv.NotFoundError:
            # server predates the bulk route (404 maps to NotFoundError;
            # a server WITH the route reports per-item errors in-band)
            return super().bind_many(bindings)
        out: list[tuple[Obj | None, Exception | None]] = []
        for item in resp.get("items") or ():
            if item.get("status") == "Success":
                out.append(({}, None))
            elif item.get("reason") == "BindConflict":
                out.append((None, _bind_conflict_from(item)))
            else:
                err = self._BULK_ERRORS.get(item.get("reason"), HTTPError)
                msg = item.get("message", "")
                out.append((None, err(item.get("code", 500), msg)
                            if err is HTTPError else err(msg)))
        while len(out) < len(bindings):  # pragma: no cover - short reply
            out.append((None, HTTPError(500, "missing bulk result")))
        return out

    def create_events(self, events: list[Obj]) -> None:
        """Event broadcaster flush: one bulk POST per burst (the generic
        base writes one by one)."""
        self.create_bulk("events", events)

    def create_bulk(self, resource: str, objs: list[Obj]) -> None:
        """Bulk create through ONE request: POST {kind: List, items} to
        the collection (server: _post_bulk_create -> kv.create_many).
        Raises on the first per-item failure, matching
        LocalClient.create_bulk's contract (the event broadcaster's
        flush catches StoreError, keeping events fire-and-forget)."""
        if not objs:
            return
        try:
            resp = self._request("POST",
                                 self._path(resource,
                                            meta.namespace(objs[0])),
                                 {"kind": "List", "apiVersion": "v1",
                                  "items": objs})
        except kv.NotFoundError:
            for o in objs:  # server predates the bulk route
                self.create(resource, o)
            return
        for item in resp.get("items") or ():
            if item.get("status") != "Success":
                err = self._BULK_ERRORS.get(item.get("reason"))
                msg = item.get("message", "")
                raise err(msg) if err is not None else HTTPError(
                    item.get("code", 500), msg)

    def evict(self, namespace: str, name: str) -> Obj:
        """POST pods/{name}/eviction — PDB-gated delete (429 when blocked)."""
        path = self._path("pods", namespace, name) + "/eviction"
        return self._request("POST", path, {
            "kind": "Eviction", "apiVersion": "policy/v1",
            "metadata": {"name": name, "namespace": namespace}})

    def update_status(self, resource: str, obj: Obj) -> Obj:
        path = self._path(resource, meta.namespace(obj), meta.name(obj)) + "/status"
        return self._request("PUT", path, obj)

    def scale(self, resource: str, namespace: str, name: str,
              replicas: int | None = None) -> Obj:
        path = self._path(resource, namespace, name) + "/scale"
        if replicas is None:
            return self._request("GET", path)
        return self._request("PUT", path, {"spec": {"replicas": replicas}})
