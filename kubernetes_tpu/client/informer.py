"""Reflector + shared informer + lister.

Reference semantics:
  staging/src/k8s.io/client-go/tools/cache/reflector.go:256 (ListAndWatch:
    list -> sync handlers -> watch from list rv; on "too old" -> relist)
  tools/cache/shared_informer.go (one informer per resource shared by all
    consumers; handlers receive add/update/delete in event order)
  tools/cache/thread_safe_store.go (indexer) + listers

Differences from the reference, on purpose:
  * No DeltaFIFO: our store's Watch already delivers a linearized, complete
    event stream per resource (same lock that orders writes orders events),
    so the informer thread applies events straight to the indexer and calls
    handlers synchronously on that single thread.  This preserves the only
    property consumers rely on — per-resource events are delivered in order,
    and the indexer is updated before handlers run.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Callable

from ..api import meta
from ..api.meta import Obj
from ..store import kv
from ..utils import fasthost
from .clientset import Client

logger = logging.getLogger(__name__)

EventHandler = Callable[[str, Obj, Obj | None], None]
# signature: (event_type, obj, old_obj_or_None)


class Informer:
    """List+watch one resource into an in-memory indexer; fan out to handlers."""

    def __init__(self, client: Client, resource: str):
        self.client = client
        self.resource = resource
        # _lock guards the indexer for READERS (get/list); _dispatch_lock
        # serializes handler invocation + registration.  Split so readers
        # never wait behind handler execution (the old single lock cost
        # ~20µs of contention per event at bench scale).  Lock order:
        # _dispatch_lock -> _lock, never the reverse.
        self._lock = threading.RLock()
        self._dispatch_lock = threading.RLock()
        self._indexer: dict[str, Obj] = {}  # guarded-by: _lock
        self._handlers: list[EventHandler] = []  # guarded-by: _dispatch_lock
        self._bulk_handlers: list[Callable[[list], None]] = []  # guarded-by: _dispatch_lock
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # relist accounting ({reason: count}, drained into the
        # informer_relist_total counter) + seeded per-informer jitter so
        # every informer's retry clock is decorrelated deterministically
        self._relist_pending: dict[str, int] = {}  # guarded-by: _lock
        self._retry_rng = random.Random(
            hash(resource) & 0xFFFFFFFF)
        # deterministic per-INSTANCE relist offset (scale-out): N
        # processes restarting after an apiserver blip would otherwise
        # thundering-herd it with simultaneous LISTs; the factory sets
        # this to a fixed offset derived from the instance index
        self.relist_stagger = 0.0
        # warm-start seed (prime()): consumed ONCE in place of the first
        # LIST, so a checkpointed restart replays only the watch delta
        # since the checkpoint's resourceVersion.  last_rv tracks the
        # newest revision applied (list rv, then per watch batch) — the
        # value a checkpoint records so the next restart can prime.
        self._warm: tuple[list, int] | None = None
        self.last_rv = 0

    # -- lister ----------------------------------------------------------

    def get(self, namespace: str, name: str) -> Obj | None:
        with self._lock:
            key = f"{namespace}/{name}" if namespace else name
            return self._indexer.get(key)

    def get_by_key(self, key: str) -> Obj | None:
        with self._lock:
            return self._indexer.get(key)

    def list(self, namespace: str | None = None) -> list[Obj]:
        with self._lock:
            if namespace:
                prefix = namespace + "/"
                return [o for k, o in self._indexer.items() if k.startswith(prefix)]
            return list(self._indexer.values())

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._indexer.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._indexer)

    # -- lifecycle -------------------------------------------------------

    def add_event_handler(self, handler: EventHandler) -> None:
        """Register a handler. If already synced, replays adds (shared_informer
        semantics: late handlers get a full resync of existing objects).
        Registration takes the dispatch lock, so it is atomic with respect
        to in-flight events: the handler sees either the replayed state or
        the live event stream from its registration point, never a gap."""
        with self._dispatch_lock:
            self._handlers.append(handler)
            if self._synced.is_set():
                with self._lock:
                    objs = list(self._indexer.values())
                for obj in objs:
                    handler(kv.ADDED, obj, None)

    def add_bulk_event_handler(self, handler: Callable[[list], None]) -> None:
        """Register a BULK handler: called with a list of
        (event_type, obj, old) triples covering a whole watch burst, after
        per-event handlers.  Consumers with per-event lock overhead (the
        scheduler's queue/cache) use this to amortize it; semantics are
        identical to receiving the triples one at a time, in order."""
        with self._dispatch_lock:
            self._bulk_handlers.append(handler)
            if self._synced.is_set():
                with self._lock:
                    objs = list(self._indexer.values())
                if objs:
                    handler([(kv.ADDED, obj, None) for obj in objs])

    def prime(self, objs: list, rv: int) -> None:
        """Warm-start seed: the reflector's next cycle consumes (objs,
        rv) in place of its initial LIST and opens the watch at `rv`, so
        a process restarting from a checkpoint replays only the delta
        since it — deletions included, as ordinary DELETED events.  The
        seed is one-shot: if the watch window at `rv` has been compacted
        (TooOldError) the normal relist recovery does a REAL list, so a
        stale seed costs one extra round trip, never wrong state.  Call
        before start()."""
        self._warm = (list(objs), int(rv))

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"informer-{self.resource}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def wait_for_cache_sync(self, timeout: float = 30.0) -> bool:
        return self._synced.wait(timeout)

    @property
    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- reflector loop --------------------------------------------------

    def _run(self) -> None:
        consecutive_failures = 0
        while not self._stop.is_set():
            try:
                self._list_and_watch()
                consecutive_failures = 0
            except kv.TooOldError:
                # the relist itself recovers the window: no backoff, but
                # the instance stagger still applies — every instance
                # overruns its watch window at the same moment when the
                # store compacts, and N synchronized LISTs is exactly the
                # herd the offset exists to spread
                logger.info("informer %s: watch too old, relisting", self.resource)
                self._tally_relist("too_old")
                consecutive_failures = 0
                if self.relist_stagger:
                    self._stop.wait(self.relist_stagger)
                continue
            except Exception:  # pragma: no cover - defensive, crash-only restart
                # jittered exponential backoff: a down store must not get a
                # synchronized relist storm from every informer the moment
                # it returns (they'd all retry in lockstep on a fixed sleep)
                self._tally_relist("error")
                consecutive_failures += 1
                delay = min(30.0, 1.0 * 2 ** (consecutive_failures - 1))
                delay *= 0.5 + self._retry_rng.random()  # +/-50%
                delay += self.relist_stagger  # deterministic instance offset
                logger.exception("informer %s: list/watch failed, retrying "
                                 "in %.1fs", self.resource, delay)
                self._stop.wait(delay)

    def _tally_relist(self, reason: str) -> None:
        with self._lock:
            self._relist_pending[reason] = (
                self._relist_pending.get(reason, 0) + 1)

    def drain_relist_total(self) -> dict[str, int]:
        """Pop the pending {reason: count} relist tallies (aggregated per
        resource by SharedInformerFactory.drain_relist_total and drained
        into informer_relist_total by Scheduler.expose_metrics)."""
        with self._lock:
            out, self._relist_pending = self._relist_pending, {}
        return out

    def _list_and_watch(self) -> None:
        warm, self._warm = self._warm, None
        if warm is not None:
            items, rv = warm
        else:
            items, rv = self.client.list(self.resource)
        self.last_rv = rv
        fresh = {meta.namespaced_name(o): o for o in items}
        # Each event: indexer update + handler calls under _dispatch_lock
        # (atomic wrt handler registration); the indexer write itself under
        # the narrow _lock so get/list readers never wait behind handler
        # execution (the old single lock cost ~20µs x 2 events/pod).
        with self._dispatch_lock:
            with self._lock:
                old = self._indexer
                self._indexer = fresh
            # Replace semantics: diff old vs new and emit synthetic events
            # (DeltaFIFO Replace -> Sync/Delete).
            triples = []
            for key, obj in fresh.items():
                prev = old.get(key)
                if prev is None:
                    triples.append((kv.ADDED, obj, None))
                elif meta.resource_version(prev) != meta.resource_version(obj):
                    triples.append((kv.MODIFIED, obj, prev))
            for key, prev in old.items():
                if key not in fresh:
                    triples.append((kv.DELETED, prev, None))
            self._dispatch_all(triples)
            self._synced.set()  # inside the lock: registration either
            # replays this state or receives the live stream — no gap

        w = self.client.watch(self.resource, since_rv=rv)
        try:
            while not self._stop.is_set():
                evs = w.next_batch(timeout=0.5)
                if not evs:
                    if w.stopped:
                        return
                    continue
                # apply the whole burst to the indexer under ONE lock
                # acquisition, then dispatch; per-resource ordering is
                # preserved (single informer thread, in-order drain).
                # The apply itself is one fasthost C pass when built
                # (pure-Python fallback is the identical loop).
                with self._dispatch_lock:
                    with self._lock:
                        triples = fasthost.watch_apply(evs, self._indexer)
                    self.last_rv = evs[-1].revision
                    self._dispatch_all(triples)
        finally:
            w.stop()

    def _dispatch_all(self, triples: list) -> None:
        """Run per-event handlers event-by-event, then bulk handlers once.
        Caller holds _dispatch_lock."""
        if not triples:
            return
        for type_, obj, old in triples:
            for h in self._handlers:
                try:
                    h(type_, obj, old)
                except Exception:  # pragma: no cover
                    logger.exception("informer %s: handler error on %s",
                                     self.resource, type_)
        for bh in self._bulk_handlers:
            try:
                bh(triples)
            except Exception:  # pragma: no cover
                logger.exception("informer %s: bulk handler error",
                                 self.resource)

    def _dispatch(self, type_: str, obj: Obj, old: Obj | None) -> None:
        for h in self._handlers:
            try:
                h(type_, obj, old)
            except Exception:  # pragma: no cover
                logger.exception("informer %s: handler error on %s", self.resource, type_)


class SharedInformerFactory:
    """One Informer per resource, shared (client-go informers.SharedInformerFactory)."""

    def __init__(self, client: Client):
        self.client = client
        self._lock = threading.Lock()
        self._informers: dict[str, Informer] = {}
        self._started = False
        self._relist_stagger = 0.0

    def set_relist_stagger(self, offset: float) -> None:
        """Set the deterministic relist offset (seconds) on every
        informer, existing and future — wired from the scaleOut: stanza
        as a fixed function of the instance index so N processes never
        relist in lockstep."""
        with self._lock:
            self._relist_stagger = max(0.0, offset)
            informers = list(self._informers.values())
        for inf in informers:
            inf.relist_stagger = self._relist_stagger

    def informer(self, resource: str) -> Informer:
        with self._lock:
            inf = self._informers.get(resource)
            if inf is None:
                inf = self._informers[resource] = Informer(self.client, resource)
                inf.relist_stagger = self._relist_stagger
                if self._started:
                    # factory already running: late informers start eagerly
                    # (client-go restarts the factory; we just start the one)
                    inf.start()
            return inf

    def start(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
            self._started = True
        for inf in informers:
            inf.start()

    def wait_for_cache_sync(self, timeout: float = 30.0) -> bool:
        with self._lock:
            informers = list(self._informers.values())
        return all(inf.wait_for_cache_sync(timeout) for inf in informers)

    def stop(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.stop()

    def drain_relist_total(self) -> dict[tuple[str, str], int]:
        """Pop {(resource, reason): count} relist tallies across every
        informer (feeds the informer_relist_total counter)."""
        with self._lock:
            informers = list(self._informers.items())
        out: dict[tuple[str, str], int] = {}
        for resource, inf in informers:
            for reason, n in inf.drain_relist_total().items():
                key = (resource, reason)
                out[key] = out.get(key, 0) + n
        return out
