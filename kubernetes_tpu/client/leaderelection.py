"""Leader election via Lease CAS.

Reference: staging/src/k8s.io/client-go/tools/leaderelection/
  leaderelection.go:177 (Run), :200 (acquire loop), :241-272 (renew),
  :317 (tryAcquireOrRenew) and resourcelock/leaselock.go:31.

Crash-only HA: every control-plane component (scheduler, controller
manager) runs N replicas; one holds the Lease and renews it; on renewal
failure it stops leading and another replica acquires.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Callable

from ..api import meta
from ..store import kv
from .clientset import LEASES, Client

logger = logging.getLogger(__name__)


class LeaderElector:
    def __init__(self, client: Client, lock_name: str,
                 identity: str | None = None,
                 lease_duration: float = 15.0,
                 renew_deadline: float = 10.0,
                 retry_period: float = 2.0,
                 on_started_leading: Callable[[], None] | None = None,
                 on_stopped_leading: Callable[[], None] | None = None,
                 namespace: str = "kube-system"):
        self.client = client
        self.lock_name = lock_name
        self.namespace = namespace
        self.identity = identity or f"{lock_name}-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading or (lambda: None)
        self.on_stopped_leading = on_stopped_leading or (lambda: None)
        self._stop = threading.Event()
        self._leading = False
        self._thread: threading.Thread | None = None

    @property
    def is_leader(self) -> bool:
        return self._leading

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"leaderelection-{self.lock_name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._leading:
            self._release()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                if not self._leading:
                    self._leading = True
                    logger.info("%s became leader of %s", self.identity,
                                self.lock_name)
                    self.on_started_leading()
            else:
                if self._leading:
                    self._leading = False
                    logger.info("%s lost leadership of %s", self.identity,
                                self.lock_name)
                    self.on_stopped_leading()
            self._stop.wait(self.retry_period)

    # tryAcquireOrRenew (leaderelection.go:317)
    def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        try:
            lease = self.client.get(LEASES, self.namespace, self.lock_name)
        except kv.NotFoundError:
            lease = meta.new_object("Lease", self.lock_name, self.namespace)
            lease["spec"] = {"holderIdentity": self.identity,
                            "acquireTime": now, "renewTime": now,
                            "leaseDurationSeconds": self.lease_duration}
            try:
                self.client.create(LEASES, lease)
                return True
            except kv.StoreError:
                return False
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        expired = now > spec.get("renewTime", 0) + spec.get(
            "leaseDurationSeconds", self.lease_duration)
        if holder != self.identity and not expired:
            return False

        def claim(obj):
            s = obj.setdefault("spec", {})
            cur_holder = s.get("holderIdentity")
            cur_expired = time.time() > s.get("renewTime", 0) + s.get(
                "leaseDurationSeconds", self.lease_duration)
            if cur_holder != self.identity and not cur_expired:
                raise kv.ConflictError("lease held")
            if cur_holder != self.identity:
                s["acquireTime"] = time.time()
            s["holderIdentity"] = self.identity
            s["renewTime"] = time.time()
            s["leaseDurationSeconds"] = self.lease_duration
            return obj

        try:
            self.client.guaranteed_update(LEASES, self.namespace,
                                          self.lock_name, claim)
            return True
        except kv.StoreError:
            return False

    def _release(self) -> None:
        def drop(obj):
            s = obj.setdefault("spec", {})
            if s.get("holderIdentity") == self.identity:
                s["holderIdentity"] = ""
                s["renewTime"] = 0
            return obj
        try:
            self.client.guaranteed_update(LEASES, self.namespace,
                                          self.lock_name, drop)
        except kv.StoreError:
            pass
