"""Work queues for controllers.

Reference semantics: staging/src/k8s.io/client-go/util/workqueue/
  queue.go          - dedup via dirty/processing sets; Get/Done protocol
  delaying_queue.go - AddAfter via time-ordered heap
  default_rate_limiters.go - per-item exponential backoff + overall bucket

An item added while being processed is remembered (dirty) and re-queued when
Done() is called — this is the exact property controllers rely on to never
miss an event and never process the same key concurrently.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Hashable


class WorkQueue:
    """FIFO with dedup + in-flight tracking (workqueue/queue.go)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._queue: list[Hashable] = []
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._shutting_down = False

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: float | None = None) -> tuple[Any, bool]:
        """Returns (item, shutdown). Blocks until an item or shutdown."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutting_down:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None, False
                self._cond.wait(remaining)
            if not self._queue:
                return None, True
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item, False

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def shut_down(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._cond:
            return self._shutting_down


class DelayingQueue(WorkQueue):
    """WorkQueue + add_after (workqueue/delaying_queue.go)."""

    def __init__(self) -> None:
        super().__init__()
        self._heap: list[tuple[float, int, Hashable]] = []
        self._seq = 0
        self._waiter = threading.Condition()
        self._loop = threading.Thread(target=self._waiting_loop, daemon=True)
        self._loop.start()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._waiter:
            self._seq += 1
            heapq.heappush(self._heap, (time.monotonic() + delay, self._seq, item))
            self._waiter.notify()

    def _waiting_loop(self) -> None:
        while True:
            with self._waiter:
                if self.shutting_down:
                    return
                now = time.monotonic()
                ready: list[Hashable] = []
                while self._heap and self._heap[0][0] <= now:
                    ready.append(heapq.heappop(self._heap)[2])
                wait = (self._heap[0][0] - now) if self._heap else 0.2
            for item in ready:
                self.add(item)
            with self._waiter:
                if not self.shutting_down:
                    self._waiter.wait(min(wait, 0.2))

    def shut_down(self) -> None:
        super().shut_down()
        with self._waiter:
            self._waiter.notify_all()


class RateLimiter:
    """Per-item exponential backoff (ItemExponentialFailureRateLimiter)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self._base = base_delay
        self._max = max_delay
        self._failures: dict[Hashable, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
            return min(self._base * (2 ** n), self._max)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue(DelayingQueue):
    """DelayingQueue + rate limiter (workqueue/rate_limiting_queue.go)."""

    def __init__(self, rate_limiter: RateLimiter | None = None):
        super().__init__()
        self.rate_limiter = rate_limiter or RateLimiter()

    def add_rate_limited(self, item: Hashable) -> None:
        self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Hashable) -> None:
        self.rate_limiter.forget(item)
