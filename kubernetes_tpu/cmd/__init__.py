"""Component entry points (reference: cmd/kube-* binaries).

Each module is runnable: `python -m kubernetes_tpu.cmd.<component>`.
See cmd/cluster.py for an all-in-one local cluster.
"""
