"""kube-apiserver entry point (reference: cmd/kube-apiserver)."""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="tpu-apiserver")
    ap.add_argument("--bind-address", default="127.0.0.1")
    ap.add_argument("--secure-port", type=int, default=8080)
    ap.add_argument("--token", default=None, help="static bearer token authn")
    ap.add_argument("--token-file", default=None,
                    help="token auth file: one 'token,user,group1|group2' "
                         "line per credential (reference --token-auth-file)")
    ap.add_argument("--authorization-mode", default="AlwaysAllow",
                    choices=["AlwaysAllow", "RBAC"])
    ap.add_argument("--encrypt-secrets", action="store_true",
                    help="KMS envelope encryption of Secrets at rest "
                         "(EncryptionConfiguration kms provider equivalent)")
    ap.add_argument("--data-dir", default=None,
                    help="directory for the store's WAL + snapshots; "
                         "omitting it runs memory-only (no durability)")
    ap.add_argument("--enable-default-admission", action="store_true",
                    help="run the in-tree admission chain (the bench's "
                         "front-door configuration)")
    ap.add_argument("--disable-admission-plugins", default="",
                    help="comma-separated plugin names to remove from "
                         "the default chain (the reference harness "
                         "disables ServiceAccount,TaintNodesByCondition,"
                         "Priority when no controllers run — "
                         "scheduler_perf/util.go:84-85)")
    ap.add_argument("-v", "--verbosity", type=int, default=1)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbosity > 4 else logging.INFO)

    from ..apiserver import APIServer
    from ..store import kv

    transformers = None
    if args.encrypt_secrets:
        import os
        from ..store.encryption import EnvelopeTransformer, LocalKMS
        key_file = None
        if args.data_dir:  # durable store needs a durable KEK ring
            os.makedirs(args.data_dir, exist_ok=True)
            key_file = os.path.join(args.data_dir, "kms-keys.json")
        transformers = {"secrets": EnvelopeTransformer(
            LocalKMS(key_file=key_file))}
    tokens = None
    if args.token_file:
        tokens = {}
        with open(args.token_file) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                tok, user, *rest = line.split(",")
                groups = tuple(g for g in (rest[0].split("|") if rest else ())
                               if g)
                tokens[tok] = (user, groups)
    store = kv.MemoryStore(history=1_000_000, transformers=transformers,
                           durable_dir=args.data_dir)
    server = APIServer(
        store, host=args.bind_address, port=args.secure_port,
        token=args.token, tokens=tokens,
        enable_rbac=args.authorization_mode == "RBAC",
        enable_default_admission=args.enable_default_admission,
        disable_admission_plugins=frozenset(
            p for p in args.disable_admission_plugins.split(",")
            if p)).start()
    print(f"apiserver listening on {server.url}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    server.stop()


if __name__ == "__main__":
    main()
