"""All-in-one local cluster (reference: the 'hack/local-up-cluster.sh'
developer experience + kubeadm's role as the bootstrap path).

Starts apiserver + scheduler + controller-manager + N hollow nodes in one
process, serving the REST API so kubectl and other processes can attach.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main(argv=None) -> None:
    from ..utils.gctune import tune_for_throughput
    tune_for_throughput()
    ap = argparse.ArgumentParser(prog="tpu-cluster")
    ap.add_argument("--secure-port", type=int, default=8080)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--tpu-batch", action="store_true")
    ap.add_argument("--tpu-worker", default=None,
                    help="URL of an external tpu-worker process "
                         "(cmd/tpu_worker.py); default runs the device "
                         "backend in-process")
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--devices-per-node", type=int, default=0,
                    help="give each hollow node N google.com/tpu devices "
                         "(exercises the kubelet device/topology managers)")
    ap.add_argument("--data-dir", default=None,
                    help="directory for the store's WAL + snapshots; "
                         "omitting it runs memory-only (no durability)")
    ap.add_argument("-v", "--verbosity", type=int, default=1)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbosity > 4 else logging.INFO)

    from ..apiserver import APIServer
    from ..client.clientset import LocalClient
    from ..client.informer import SharedInformerFactory
    from ..controllers import ControllerManager
    from ..controllers.endpoints import EndpointsController
    from ..kubelet import KubeletServer, start_hollow_nodes
    from ..scheduler import Profile, Scheduler, new_default_framework
    from ..store import kv

    store = kv.MemoryStore(history=1_000_000, durable_dir=args.data_dir)
    server = APIServer(store, port=args.secure_port).start()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)

    fw = new_default_framework(client, factory)
    if args.tpu_batch:
        from ..ops.flatten import Caps
        caps = Caps(n_cap=max(1024, args.nodes * 2))
        if args.tpu_worker:
            from ..ops.remote import RemoteTPUBatchBackend
            backend = RemoteTPUBatchBackend(args.tpu_worker, caps,
                                            batch_size=args.batch_size)
        else:
            from ..ops.backend import TPUBatchBackend
            backend = TPUBatchBackend(caps, batch_size=args.batch_size)
        backend.warmup()
        profile = Profile(fw, batch_backend=backend, batch_size=args.batch_size)
    else:
        profile = Profile(fw)
    sched = Scheduler(client, factory, {"default-scheduler": profile})
    mgr = ControllerManager(client, factory)
    endpoints = EndpointsController(client, factory)

    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    mgr.run()
    endpoints.run()
    kubelet_server = KubeletServer().start()
    if args.devices_per_node > 0:
        from ..kubelet import HollowKubelet
        from ..kubelet.cm import ContainerManager, DevicePlugin
        kubelets = []
        num_numa = 2
        for i in range(args.nodes):
            cmgr = ContainerManager(num_cpus=32, memory_bytes=256 << 30,
                                    num_numa=num_numa)
            cmgr.devices.register(DevicePlugin("google.com/tpu", {
                f"tpu{d}": d * num_numa // args.devices_per_node
                for d in range(args.devices_per_node)}))
            kubelets.append(HollowKubelet(
                client, factory, f"hollow-{i}", container_manager=cmgr,
                kubelet_server=kubelet_server).start())
    else:
        kubelets = start_hollow_nodes(client, factory, args.nodes,
                                      kubelet_server=kubelet_server)

    print(f"cluster up: apiserver={server.url} nodes={args.nodes} "
          f"scheduler={'tpu-batch' if args.tpu_batch else 'per-pod'}")
    print(f"try: python -m kubernetes_tpu.cli.kubectl --server {server.url} "
          f"get nodes")
    stop = threading.Event()
    from ..scheduler.debugger import CacheDebugger
    CacheDebugger(sched, client).listen_for_signal()  # SIGUSR2 dump+compare
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    for k in kubelets:
        k.stop()
    kubelet_server.stop()
    endpoints.stop()
    mgr.stop()
    sched.stop()
    server.stop()


if __name__ == "__main__":
    main()
