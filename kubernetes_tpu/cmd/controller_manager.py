"""kube-controller-manager entry point (reference: cmd/kube-controller-manager)."""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="tpu-controller-manager")
    ap.add_argument("--server", default="http://127.0.0.1:8080")
    ap.add_argument("--token", default=None)
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--controllers", default="*",
                    help="comma list or * (deployment,replicaset,job,"
                         "garbagecollector,nodelifecycle,endpoints)")
    ap.add_argument("-v", "--verbosity", type=int, default=1)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbosity > 4 else logging.INFO)

    from ..client.http_client import HTTPClient
    from ..client.informer import SharedInformerFactory
    from ..controllers import ControllerManager
    from ..controllers.endpoints import EndpointsController
    from ..controllers.manager import DEFAULT_CONTROLLERS

    client = HTTPClient.from_url(args.server, args.token)
    factory = SharedInformerFactory(client)
    names = (DEFAULT_CONTROLLERS if args.controllers == "*"
             else tuple(n for n in args.controllers.split(",")
                        if n != "endpoints"))
    mgr = ControllerManager(client, factory, controllers=names,
                            leader_elect=args.leader_elect)
    endpoints = (EndpointsController(client, factory)
                 if args.controllers in ("*",) or "endpoints" in args.controllers
                 else None)
    factory.start()
    factory.wait_for_cache_sync()
    mgr.run()
    if endpoints:
        endpoints.run()
    print(f"controller-manager running: {', '.join(names)}"
          + (", endpoints" if endpoints else ""))
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    if endpoints:
        endpoints.stop()
    mgr.stop()


if __name__ == "__main__":
    main()
