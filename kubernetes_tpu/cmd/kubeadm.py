"""Cluster bootstrap phases (the kubeadm-equivalent).

Reference: cmd/kubeadm — `init` runs an ordered phase list (preflight,
certs, control-plane, upload-config, bootstrap-token; app/phases/),
prints the join command; `join` validates the token against the
cluster-info ConfigMap's JWS signature (app/phases/bootstraptoken) and
registers the node.

Mapped to this stack: `init` starts the in-process control plane
(apiserver + scheduler + controller-manager incl. bootstrapsigner),
mints a bootstrap token Secret, uploads the kubeadm-config ConfigMap and
prints the join line.  `join --token` fetches kube-public/cluster-info
WITHOUT credentials, verifies the HMAC signature with the token secret
(the trust bootstrap), then registers a hollow node.
"""

from __future__ import annotations

import argparse
import base64
import hashlib
import hmac
import json
import logging
import secrets as pysecrets
import signal
import threading
import time
import urllib.request

logger = logging.getLogger(__name__)

PHASES_INIT = ("preflight", "certs", "control-plane", "kubeconfig",
               "upload-config", "bootstrap-token")


def _phase(name: str, msg: str) -> None:
    print(f"[{name}] {msg}")


def _kubeconfig(server_url: str, ca_pem: str, user: str,
                token: str | None = None, cert_pem: str | None = None,
                key_pem: str | None = None) -> dict:
    """A kubeconfig document binding endpoint + CA + credential (the
    reference's kubeconfig phase: app/phases/kubeconfig) — client-cert
    credentials by default, bearer token for bootstrap identities."""
    cred: dict = {}
    if cert_pem is not None:
        cred["client-certificate-data"] = base64.b64encode(
            cert_pem.encode()).decode()
        cred["client-key-data"] = base64.b64encode(
            (key_pem or "").encode()).decode()
    if token is not None:
        cred["token"] = token
    return {
        "apiVersion": "v1", "kind": "Config",
        "clusters": [{"name": "kubernetes", "cluster": {
            "server": server_url,
            "certificate-authority-data": base64.b64encode(
                ca_pem.encode()).decode()}}],
        "users": [{"name": user, "user": cred}],
        "contexts": [{"name": f"{user}@kubernetes", "context": {
            "cluster": "kubernetes", "user": user}}],
        "current-context": f"{user}@kubernetes",
    }


def _write_kubeconfig(cert_dir, fname: str, doc: dict) -> str:
    import os

    import yaml
    os.makedirs(cert_dir, exist_ok=True)
    path = os.path.join(cert_dir, fname)
    with open(path, "w") as f:
        os.fchmod(f.fileno(), 0o600)
        yaml.safe_dump(doc, f, sort_keys=False)
    return path


def init(args) -> None:
    from ..apiserver import APIServer
    from ..client.clientset import CONFIGMAPS, SECRETS, LocalClient
    from ..client.informer import SharedInformerFactory
    from ..controllers import ControllerManager
    from ..controllers.bootstrap import BOOTSTRAP_TOKEN_TYPE, BootstrapSigner
    from ..api import meta
    from ..controllers.certificates import ClusterCA
    from ..scheduler import Profile, Scheduler, new_default_framework
    from ..store import kv

    # preflight (app/preflight/checks.go: port availability &c.)
    _phase("preflight", "running pre-flight checks")
    import socket
    with socket.socket() as s:
        if s.connect_ex(("127.0.0.1", args.secure_port)) == 0:
            raise SystemExit(
                f"[preflight] port {args.secure_port} already in use")

    import os

    _phase("certs", "generating cluster CA + apiserver serving cert")
    from ..apiserver import authn as authnlib
    ca = ClusterCA.shared()  # materialized here; published by root-ca ctrl
    os.makedirs(args.cert_dir, exist_ok=True)
    tls = authnlib.write_serving_bundle(ca, args.cert_dir)
    _phase("certs", f"wrote {tls['client_ca_file']}, {tls['cert_file']}")

    _phase("control-plane", "starting apiserver (TLS + client-cert authn "
           "+ RBAC + SA tokens), scheduler, controller-manager")
    # component credentials: each control-plane identity gets its own
    # client certificate signed by the cluster CA (app/phases/kubeconfig);
    # the apiserver authenticates them via the client-CA x509 path
    comp_certs = {
        "admin": authnlib.issue_cert(ca, "kubernetes-admin",
                                     ("system:masters",)),
        "scheduler": authnlib.issue_cert(ca, "system:kube-scheduler"),
        "controller-manager": authnlib.issue_cert(
            ca, "system:kube-controller-manager"),
    }
    store = kv.MemoryStore(history=1_000_000)
    server = APIServer(store, port=args.secure_port, tls=tls,
                       enable_rbac=True, bootstrap_token_auth=True,
                       enable_service_accounts=True).start()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    fw = new_default_framework(client, factory)
    sched = Scheduler(client, factory, {"default-scheduler": Profile(fw)})
    mgr = ControllerManager(client, factory)
    signer = BootstrapSigner(client, factory, server_url=server.url,
                             ca_pem=ClusterCA.shared().ca_pem())
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    mgr.run()
    signer.run()

    _phase("kubeconfig", "writing admin/scheduler/controller-manager "
           "kubeconfig files (client-cert credentials)")
    for comp, fname, user in (("admin", "admin.conf", "kubernetes-admin"),
                              ("scheduler", "scheduler.conf",
                               "system:kube-scheduler"),
                              ("controller-manager",
                               "controller-manager.conf",
                               "system:kube-controller-manager")):
        cert_pem, key_pem = comp_certs[comp]
        path = _write_kubeconfig(args.cert_dir, fname, _kubeconfig(
            server.url, ca.ca_pem(), user,
            cert_pem=cert_pem, key_pem=key_pem))
        _phase("kubeconfig", f"wrote {path}")

    _phase("upload-config", "storing kubeadm-config ConfigMap")
    cfg = meta.new_object("ConfigMap", "kubeadm-config", "kube-system")
    cfg["data"] = {"ClusterConfiguration": json.dumps(
        {"kubernetesVersion": "tpu", "controlPlaneEndpoint": server.url})}
    try:
        client.create(CONFIGMAPS, cfg)
    except kv.AlreadyExistsError:
        pass

    _phase("bootstrap-token", "creating bootstrap token")
    token_id = pysecrets.token_hex(3)
    token_secret = pysecrets.token_hex(8)
    tok = meta.new_object("Secret", f"bootstrap-token-{token_id}",
                          "kube-system")
    tok["type"] = BOOTSTRAP_TOKEN_TYPE
    tok["data"] = {"token-id": token_id, "token-secret": token_secret,
                   "expiration": str(time.time() + 24 * 3600),
                   "usage-bootstrap-authentication": "true"}
    client.create(SECRETS, tok)

    print()
    print("Your control plane initialized successfully!")
    print("To join a node run:\n")
    print(f"  python -m kubernetes_tpu.cmd.kubeadm join "
          f"--server {server.url} --token {token_id}.{token_secret}\n")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    signer.stop()
    mgr.stop()
    sched.stop()
    factory.stop()
    server.stop()


def join(args) -> None:
    from ..client.http_client import HTTPClient
    from ..client.informer import SharedInformerFactory
    from ..kubelet import HollowKubelet

    token_id, _, token_secret = args.token.partition(".")
    if not token_id or not token_secret:
        raise SystemExit("token must be <id>.<secret>")

    # discovery (bootstraptoken/clusterinfo): UNAUTHENTICATED fetch of
    # cluster-info; trust is established by verifying the JWS/HMAC made
    # with the shared token secret
    _phase("discovery", f"fetching cluster-info from {args.server}")
    url = (f"{args.server}/api/v1/namespaces/kube-public/"
           f"configmaps/cluster-info")
    # pre-trust fetch: no CA is known yet, so TLS verification is off —
    # trust comes from the JWS signature + endpoint pin below, after
    # which the embedded CA is pinned for every subsequent connection
    # (the reference's --discovery-token-unsafe-skip-ca-verification
    # bootstrap, app/discovery/token)
    import ssl as ssllib
    insecure_ctx = (ssllib._create_unverified_context()
                    if args.server.startswith("https") else None)
    with urllib.request.urlopen(url, timeout=10,
                                context=insecure_ctx) as resp:
        info = json.loads(resp.read())
    data = info.get("data") or {}
    sig = data.get(f"jws-kubeconfig-{token_id}")
    if sig is None:
        raise SystemExit(f"[discovery] no signature for token id {token_id} "
                         "in cluster-info (token unknown or expired)")
    kubeconfig = data.get("kubeconfig", "")
    want = base64.urlsafe_b64encode(hmac.new(
        token_secret.encode(), kubeconfig.encode(),
        hashlib.sha256).digest()).decode("ascii")
    if not hmac.compare_digest(want, sig):
        raise SystemExit("[discovery] cluster-info signature mismatch "
                         "(wrong token secret)")
    # the signature is only useful if the signed payload pins the cluster
    # identity: check the endpoint we dialed is the one the control plane
    # published (MITM defense; reference validates the signed kubeconfig's
    # server + CA in bootstraptoken/clusterinfo discovery)
    try:
        signed = json.loads(kubeconfig)
        signed_cluster = (signed.get("clusters") or [{}])[0].get(
            "cluster") or {}
    except (ValueError, AttributeError, IndexError):
        raise SystemExit("[discovery] signed kubeconfig is unparseable")
    signed_server = signed_cluster.get("server")
    if not signed_server:
        raise SystemExit("[discovery] signed kubeconfig carries no server "
                         "endpoint — refusing blind trust")
    if signed_server.rstrip("/") != args.server.rstrip("/"):
        raise SystemExit(f"[discovery] dialed {args.server} but the signed "
                         f"cluster-info names {signed_server} — aborting")
    ca_b64 = signed_cluster.get("certificate-authority-data")
    if ca_b64:
        ca_pem = base64.b64decode(ca_b64).decode()
        _phase("discovery", "pinned cluster CA "
               f"({hashlib.sha256(ca_pem.encode()).hexdigest()[:12]})")
    _phase("discovery", "cluster-info signature verified; endpoint bound")

    # kubelet-tls-bootstrap (app/phases/kubelet + the CSR flow): submit a
    # client CSR with the bootstrap-token identity, wait for the approve+
    # sign controllers, keep the issued certificate as the node's identity
    # material
    import os
    ca_file = None
    if ca_b64 and args.server.startswith("https"):
        os.makedirs(args.cert_dir, exist_ok=True)
        ca_file = os.path.join(args.cert_dir, "pinned-ca.crt")
        with open(ca_file, "w") as f:
            f.write(base64.b64decode(ca_b64).decode())
    tls_pin = {"ca_file": ca_file} if ca_file else (
        {} if args.server.startswith("https") else None)
    client = HTTPClient.from_url(args.server, token=args.token,
                                 tls=tls_pin)
    _phase("kubelet-tls-bootstrap",
           f"submitting CSR for node {args.node_name}")
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
        key = ec.generate_private_key(ec.SECP256R1())
        csr_pem = (x509.CertificateSigningRequestBuilder()
                   .subject_name(x509.Name([
                       x509.NameAttribute(
                           NameOID.COMMON_NAME,
                           f"system:node:{args.node_name}"),
                       x509.NameAttribute(NameOID.ORGANIZATION_NAME,
                                          "system:nodes")]))
                   .sign(key, hashes.SHA256())
                   .public_bytes(serialization.Encoding.PEM))
        csr = {"apiVersion": "certificates.k8s.io/v1",
               "kind": "CertificateSigningRequest",
               "metadata": {"name": f"node-csr-{args.node_name}"},
               "spec": {
                   "signerName":
                       "kubernetes.io/kube-apiserver-client-kubelet",
                   "usages": ["key encipherment", "digital signature",
                              "client auth"],
                   "request": base64.b64encode(csr_pem).decode()}}
        try:
            client.create("certificatesigningrequests", csr)
        except Exception as e:  # noqa: BLE001 — retried joins leave a
            # stale CSR behind; replace it (its key is gone with the old
            # process, so the old cert is useless to us anyway)
            if "exists" not in str(e).lower():
                raise
            client.delete("certificatesigningrequests", "",
                          f"node-csr-{args.node_name}")
            client.create("certificatesigningrequests", csr)
        cert_pem = None
        deadline = time.time() + 30
        while time.time() < deadline:
            cur = client.get("certificatesigningrequests", "",
                             f"node-csr-{args.node_name}")
            cert_b64 = (cur.get("status") or {}).get("certificate")
            if cert_b64:
                cert_pem = base64.b64decode(cert_b64)
                break
            time.sleep(0.2)
        if cert_pem is None:
            raise SystemExit("[kubelet-tls-bootstrap] CSR was not signed "
                             "(is the certificates controller running?)")
        os.makedirs(args.cert_dir, exist_ok=True)
        cert_path = os.path.join(args.cert_dir,
                                 f"kubelet-{args.node_name}.crt")
        with open(cert_path, "wb") as f:
            f.write(cert_pem)
        key_path = os.path.join(args.cert_dir,
                                f"kubelet-{args.node_name}.key")
        with open(key_path, "wb") as f:
            os.fchmod(f.fileno(), 0o600)
            f.write(key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption()))
        key_pem_text = key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()).decode()
        if ca_b64:
            kubeconfig_path = _write_kubeconfig(
                args.cert_dir, f"kubelet-{args.node_name}.conf",
                _kubeconfig(args.server,
                            base64.b64decode(ca_b64).decode(),
                            f"system:node:{args.node_name}",
                            cert_pem=cert_pem.decode(),
                            key_pem=key_pem_text))
            _phase("kubelet-tls-bootstrap",
                   f"wrote {cert_path}, {key_path}, {kubeconfig_path}")
        else:
            _phase("kubelet-tls-bootstrap",
                   f"wrote {cert_path}, {key_path}")
        if args.server.startswith("https"):
            # drop the bootstrap token: from here the node speaks with
            # its ISSUED certificate — system:node:<name> in
            # system:nodes, scoped by the system:node RBAC role
            client = HTTPClient.from_url(args.server, tls={
                "ca_file": ca_file, "cert_file": cert_path,
                "key_file": key_path})
            _phase("kubelet-tls-bootstrap",
                   "switched to certificate credentials "
                   f"(system:node:{args.node_name})")
    except ImportError:
        _phase("kubelet-tls-bootstrap",
               "cryptography unavailable; skipping CSR flow")

    _phase("kubelet-start", f"registering node {args.node_name}")
    factory = SharedInformerFactory(client)
    kubelet = HollowKubelet(client, factory, args.node_name)
    factory.start()
    factory.wait_for_cache_sync()
    kubelet.start()
    print(f"\nNode {args.node_name} joined the cluster.")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    kubelet.stop()
    factory.stop()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="tpu-kubeadm")
    sub = ap.add_subparsers(dest="cmd", required=True)
    ini = sub.add_parser("init", help="bootstrap a control plane")
    ini.add_argument("--secure-port", type=int, default=8080)
    ini.add_argument("--cert-dir", default="./kubeadm-pki",
                     help="where ca.crt and the kubeconfig files land")
    ini.set_defaults(fn=init)
    jn = sub.add_parser("join", help="join a node using a bootstrap token")
    jn.add_argument("--server", required=True)
    jn.add_argument("--token", required=True, help="<id>.<secret>")
    jn.add_argument("--node-name", default=f"node-{pysecrets.token_hex(3)}")
    jn.add_argument("--cert-dir", default="./kubeadm-pki",
                    help="where the issued kubelet cert/key land")
    jn.set_defaults(fn=join)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    args.fn(args)


if __name__ == "__main__":
    main()
