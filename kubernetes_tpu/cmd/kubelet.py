"""kubelet entry point — hollow node(s) (reference: cmd/kubelet + cmd/kubemark)."""

from __future__ import annotations

import argparse
import logging
import signal
import socket
import threading


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="tpu-kubelet")
    ap.add_argument("--server", default="http://127.0.0.1:8080")
    ap.add_argument("--token", default=None)
    ap.add_argument("--node-name", default=socket.gethostname())
    ap.add_argument("--cpu", default="32")
    ap.add_argument("--memory", default="256Gi")
    ap.add_argument("--max-pods", type=int, default=110)
    ap.add_argument("--hollow-nodes", type=int, default=0,
                    help="kubemark mode: register N hollow nodes instead of one")
    ap.add_argument("--full", action="store_true",
                    help="run the full kubelet (pod workers, probes, "
                         "eviction, image GC, checkpoints) instead of hollow")
    ap.add_argument("--root-dir", default=None,
                    help="checkpoint/state directory for --full")
    ap.add_argument("-v", "--verbosity", type=int, default=1)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbosity > 4 else logging.INFO)

    from ..client.http_client import HTTPClient
    from ..client.informer import SharedInformerFactory
    from ..kubelet import HollowKubelet, start_hollow_nodes

    client = HTTPClient.from_url(args.server, args.token)
    factory = SharedInformerFactory(client)
    factory.start()
    factory.wait_for_cache_sync()
    if args.hollow_nodes:
        kubelets = start_hollow_nodes(client, factory, args.hollow_nodes,
                                      cpu=args.cpu, memory=args.memory,
                                      pods=args.max_pods)
        print(f"kubemark: {args.hollow_nodes} hollow nodes registered")
    elif args.full:
        from ..kubelet.kubelet import Kubelet
        kl = Kubelet(client, factory, args.node_name, root_dir=args.root_dir,
                     cpu=args.cpu, memory=args.memory, pods=args.max_pods)
        kl.restore_state()  # crash-only restart path
        kubelets = [kl.start()]
        print(f"kubelet (full) running as node {args.node_name}")
    else:
        kubelets = [HollowKubelet(client, factory, args.node_name,
                                  cpu=args.cpu, memory=args.memory,
                                  pods=args.max_pods).start()]
        print(f"kubelet running as node {args.node_name}")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    for k in kubelets:
        k.stop()


if __name__ == "__main__":
    main()
