"""kube-proxy entry point (reference: cmd/kube-proxy)."""

from __future__ import annotations

import argparse
import logging
import signal
import socket
import threading


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="tpu-proxy")
    ap.add_argument("--server", default="http://127.0.0.1:8080")
    ap.add_argument("--token", default=None)
    ap.add_argument("--node-name", default=socket.gethostname())
    ap.add_argument("-v", "--verbosity", type=int, default=1)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbosity > 4 else logging.INFO)

    from ..client.http_client import HTTPClient
    from ..client.informer import SharedInformerFactory
    from ..proxy import ServiceProxy

    client = HTTPClient.from_url(args.server, args.token)
    factory = SharedInformerFactory(client)
    factory.start()
    factory.wait_for_cache_sync()
    proxy = ServiceProxy(client, factory, args.node_name).start()
    print(f"kube-proxy running on {args.node_name}")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    proxy.stop()


if __name__ == "__main__":
    main()
