"""kube-scheduler entry point (reference: cmd/kube-scheduler/app/server.go).

Supports the `tpu-batch` profile: --tpu-batch enables the TPU batched
Filter/Score/Assign backend for the default profile (the north star's
TPUBatchAssign), with --batch-size and --node-capacity knobs.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main(argv=None) -> None:
    from ..utils.gctune import tune_for_throughput
    tune_for_throughput()
    ap = argparse.ArgumentParser(prog="tpu-scheduler")
    ap.add_argument("--server", default="http://127.0.0.1:8080")
    ap.add_argument("--token", default=None)
    ap.add_argument("--config", default=None,
                    help="KubeSchedulerConfiguration YAML path")
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--tpu-batch", action="store_true",
                    help="enable the TPU batch scheduling backend")
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--node-capacity", type=int, default=8192)
    ap.add_argument("-v", "--verbosity", type=int, default=1)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbosity > 4 else logging.INFO)

    from ..client.http_client import HTTPClient
    from ..client.informer import SharedInformerFactory
    from ..client.leaderelection import LeaderElector
    from ..scheduler import Profile, Scheduler, new_default_framework

    client = HTTPClient.from_url(args.server, args.token)
    factory = SharedInformerFactory(client)
    if args.config:
        from ..scheduler.config import load_config, scheduler_from_config
        sched = scheduler_from_config(client, factory, load_config(args.config))
        if args.tpu_batch:
            from ..ops.backend import TPUBatchBackend
            from ..ops.flatten import Caps
            backend = TPUBatchBackend(Caps(n_cap=args.node_capacity),
                                      batch_size=args.batch_size)
            backend.warmup()
            for profile in sched.profiles.values():
                profile.batch_backend = backend
                profile.batch_size = args.batch_size
    else:
        fw = new_default_framework(client, factory)
        if args.tpu_batch:
            from ..ops.backend import TPUBatchBackend
            from ..ops.flatten import Caps
            backend = TPUBatchBackend(Caps(n_cap=args.node_capacity),
                                      batch_size=args.batch_size)
            backend.warmup()
            profile = Profile(fw, batch_backend=backend,
                              batch_size=args.batch_size)
        else:
            profile = Profile(fw)
        sched = Scheduler(client, factory, {"default-scheduler": profile})
    factory.start()
    factory.wait_for_cache_sync()

    stop = threading.Event()
    if args.leader_elect:
        elector = LeaderElector(client, "kube-scheduler",
                                on_started_leading=sched.run,
                                on_stopped_leading=stop.set)
        elector.run()
    else:
        sched.run()
    print("scheduler running"
          + (" (tpu-batch profile)" if args.tpu_batch else " (per-pod)"))
    from ..scheduler.debugger import CacheDebugger
    CacheDebugger(sched, client).listen_for_signal()  # SIGUSR2 dump+compare
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    sched.stop()


if __name__ == "__main__":
    main()
