"""Standalone TPU device worker (the out-of-process scheduling backend).

Run this next to the chip; point the scheduler's RemoteTPUBatchBackend
at its URL (ops/remote.py — BASELINE.json's scheduler<->JAX-worker shim
as a real process boundary; in-tree precedent for out-of-process
scheduling hooks: pkg/scheduler/extender.go).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="tpu-worker")
    ap.add_argument("--bind-address", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9090)
    ap.add_argument("-v", "--verbosity", type=int, default=1)
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity > 4 else logging.INFO)

    from ..ops.remote import DeviceWorker

    worker = DeviceWorker(host=args.bind_address, port=args.port).start()
    print(f"tpu-worker listening on {worker.url}")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    worker.stop()


if __name__ == "__main__":
    main()
