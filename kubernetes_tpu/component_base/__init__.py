"""Component-base: shared plumbing imported by every binary.

Reference: staging/src/k8s.io/component-base/ (SURVEY.md §2.5) — metrics
(Prometheus wrappers with stability levels), featuregate, logs, tracing,
configz, version.  Re-expressed as small Python modules; every cmd/ binary
and the scheduler import from here.
"""

from . import configz, featuregate, logs, metrics, tracing  # noqa: F401
