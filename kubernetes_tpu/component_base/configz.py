"""/configz registry.

Reference: component-base/configz — each binary installs its live
component configuration under a named key, served as JSON at /configz for
debugging.  The apiserver exposes this registry.
"""

from __future__ import annotations

import threading
from typing import Any, Dict


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._configs: Dict[str, Any] = {}

    def install(self, name: str, config: Any) -> None:
        with self._lock:
            self._configs[name] = config

    def delete(self, name: str) -> None:
        with self._lock:
            self._configs.pop(name, None)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._configs)


default_registry = Registry()


def install(name: str, config: Any) -> None:
    default_registry.install(name, config)
