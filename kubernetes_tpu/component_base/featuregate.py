"""Feature gates.

Reference: staging/src/k8s.io/component-base/featuregate/feature_gate.go —
a mutable map of named features with prerelease stages (Alpha default-off,
Beta default-on, GA locked-on), set from a --feature-gates key=value list;
plus pkg/features/kube_features.go, the per-project gate catalogue.

Semantics reproduced: unknown gate -> error; setting a GA/locked gate to a
non-default value -> error; Enabled() on an unknown gate -> error (catches
typos at call sites, as upstream does).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Mapping

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"
DEPRECATED = "DEPRECATED"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    prerelease: str = ALPHA
    lock_to_default: bool = False


class FeatureGate:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._specs: Dict[str, FeatureSpec] = {}
        self._enabled: Dict[str, bool] = {}

    def add(self, features: Mapping[str, FeatureSpec]) -> "FeatureGate":
        with self._lock:
            for name, spec in features.items():
                known = self._specs.get(name)
                if known is not None and known != spec:
                    raise ValueError("feature gate %r already registered "
                                     "with different spec" % name)
                self._specs[name] = spec
        return self

    def set_from_map(self, values: Mapping[str, bool]) -> None:
        with self._lock:
            for name, val in values.items():
                spec = self._specs.get(name)
                if spec is None:
                    raise ValueError("unrecognized feature gate: %s" % name)
                if spec.lock_to_default and val != spec.default:
                    raise ValueError(
                        "cannot set feature gate %s to %v, feature is locked"
                        " to %s" % (name, val, spec.default))
                self._enabled[name] = bool(val)

    def set(self, spec_str: str) -> None:
        """Parse 'Gate1=true,Gate2=false' (the --feature-gates flag form)."""
        values: Dict[str, bool] = {}
        for part in spec_str.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError("missing bool value for %s" % part)
            k, v = part.split("=", 1)
            lv = v.strip().lower()
            if lv not in ("true", "false"):
                raise ValueError("invalid value %r for feature gate %s"
                                 % (v, k))
            values[k.strip()] = lv == "true"
        self.set_from_map(values)

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._enabled:
                return self._enabled[name]
            spec = self._specs.get(name)
            if spec is None:
                raise ValueError("feature %r is not registered" % name)
            return spec.default

    def known_features(self) -> Dict[str, FeatureSpec]:
        with self._lock:
            return dict(self._specs)

    def deep_copy(self) -> "FeatureGate":
        fg = FeatureGate()
        with self._lock:
            fg._specs = dict(self._specs)
            fg._enabled = dict(self._enabled)
        return fg


# Project gate catalogue (pkg/features/kube_features.go analogue).  The
# TPU-specific gates control the batched backend the way upstream gates
# control scheduler features.
DEFAULT_FEATURES: Dict[str, FeatureSpec] = {
    # scheduler
    "TPUBatchAssign": FeatureSpec(default=True, prerelease=BETA),
    "TPUShardedAssign": FeatureSpec(default=True, prerelease=BETA),
    "TPUPallasKernels": FeatureSpec(default=True, prerelease=ALPHA),
    "PodSchedulingReadiness": FeatureSpec(default=False, prerelease=ALPHA),
    "PodDisruptionConditions": FeatureSpec(default=True, prerelease=BETA),
    "MinDomainsInPodTopologySpread": FeatureSpec(default=True, prerelease=BETA),
    "NodeInclusionPolicyInPodTopologySpread": FeatureSpec(default=True,
                                                          prerelease=BETA),
    # control plane
    "APIPriorityAndFairness": FeatureSpec(default=True, prerelease=BETA),
    "ServerSideApply": FeatureSpec(default=True, prerelease=GA,
                                   lock_to_default=True),
    "CustomResourceDefinitions": FeatureSpec(default=True, prerelease=GA,
                                             lock_to_default=True),
    # node
    "GracefulNodeShutdown": FeatureSpec(default=True, prerelease=BETA),
    "ContainerCheckpoint": FeatureSpec(default=False, prerelease=ALPHA),
    "KubeletTracing": FeatureSpec(default=False, prerelease=ALPHA),
}


default_feature_gate = FeatureGate().add(DEFAULT_FEATURES)
