"""Structured logging (klog v2 contract).

Reference: component-base/logs — klog InfoS/ErrorS structured key-value
logging, a JSON output format option (logs/json/register), and V-level
verbosity gating expensive paths (e.g. schedule_one.go:705 V(10) score
dumps).  Implemented over the stdlib logging module so existing module
loggers keep working; InfoS/ErrorS render 'msg key=value ...' or JSON.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Any

_state = threading.local()
_verbosity = 0
_json_format = False


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = int(v)


def get_verbosity() -> int:
    return _verbosity


def enabled(v: int) -> bool:
    """klog V(v).Enabled() — gate expensive log construction."""
    return _verbosity >= v


def set_format(fmt: str) -> None:
    """'text' (default) or 'json' (logs/json/register analogue)."""
    global _json_format
    if fmt not in ("text", "json"):
        raise ValueError("unknown log format %r" % fmt)
    _json_format = fmt == "json"


def _render(msg: str, kv: dict) -> str:
    if _json_format:
        rec = {"ts": time.time(), "msg": msg}
        rec.update({k: _jsonable(v) for k, v in kv.items()})
        return json.dumps(rec)
    if not kv:
        return msg
    return msg + " " + " ".join('%s="%s"' % (k, v) for k, v in kv.items())


def _jsonable(v: Any):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)


def info_s(logger: logging.Logger, msg: str, **kv: Any) -> None:
    logger.info(_render(msg, kv))


def error_s(logger: logging.Logger, err: Exception | None, msg: str,
            **kv: Any) -> None:
    if err is not None:
        kv = dict(kv, err=str(err))
    logger.error(_render(msg, kv))


def v(level: int):
    """Usage: logs.v(10) and logs.v(10).info_s(logger, ...)."""
    return _VLogger(level)


class _VLogger:
    __slots__ = ("level",)

    def __init__(self, level: int):
        self.level = level

    def __bool__(self) -> bool:
        return enabled(self.level)

    def info_s(self, logger: logging.Logger, msg: str, **kv: Any) -> None:
        if enabled(self.level):
            info_s(logger, msg, **kv)


def init_logs(verbosity: int = 0, fmt: str = "text",
              stream=None) -> None:
    """cli entry-point setup (component-base/logs InitLogs)."""
    set_verbosity(verbosity)
    set_format(fmt)
    logging.basicConfig(
        stream=stream or sys.stderr,
        level=logging.DEBUG if verbosity >= 4 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s] %(message)s")
