"""Prometheus-style metrics with Kubernetes stability levels.

Reference: staging/src/k8s.io/component-base/metrics — kube wraps
prometheus/client_golang with metric *stability levels* (ALPHA/STABLE),
deprecation versions (metric hidden after N+3 releases), and a shared
registry every binary exposes at /metrics.  This module reproduces that
contract: Counter/Gauge/Histogram (+ *Vec labeled variants), a Registry
with text exposition in the Prometheus format, stability/deprecation
metadata, and the exponential-bucket helper the scheduler histograms use
(pkg/scheduler/metrics/metrics.go:58 ExponentialBuckets(0.001, 2, 15)).

Thread-safe; hot-path observe() is a dict update under a per-metric lock.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

ALPHA = "ALPHA"
BETA = "BETA"
STABLE = "STABLE"


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    return [start * factor ** i for i in range(count)]


def linear_buckets(start: float, width: float, count: int) -> List[float]:
    return [start + width * i for i in range(count)]


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    # exposition format escapes backslash, double-quote AND newline in
    # label values (a raw newline would split the sample line in two)
    inner = ",".join(
        '%s="%s"' % (n, str(v).replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for n, v in zip(names, values))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


class _Metric:
    """Base: name/help/stability/deprecation + label schema."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = (),
                 stability: str = ALPHA,
                 deprecated_version: Optional[str] = None):
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self.stability = stability
        self.deprecated_version = deprecated_version
        self.hidden = False  # deprecated metrics can be hidden, not dropped
        self._lock = threading.Lock()

    def _header(self) -> List[str]:
        help_text = self.help
        if self.deprecated_version:
            help_text = ("(Deprecated since %s) " % self.deprecated_version
                         ) + help_text
        return ["# HELP %s [%s] %s" % (self.name, self.stability, help_text),
                "# TYPE %s %s" % (self.name, self.kind)]

    def collect(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, *label_values: str) -> None:
        if amount < 0:
            raise ValueError("counter cannot decrease")
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def labels(self, *label_values: str) -> "_BoundCounter":
        return _BoundCounter(self, tuple(str(v) for v in label_values))

    def value(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(tuple(str(v) for v in label_values), 0.0)

    def values(self) -> Dict[Tuple[str, ...], float]:
        """Point-in-time snapshot of every label series (Registry.gather)."""
        with self._lock:
            return dict(self._values)

    def collect(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append("%s%s %s" % (self.name,
                                    _fmt_labels(self.label_names, key),
                                    _fmt_value(v)))
        return out


class _BoundCounter:
    __slots__ = ("_c", "_key")

    def __init__(self, c: Counter, key: Tuple[str, ...]):
        self._c, self._key = c, key

    def inc(self, amount: float = 1.0) -> None:
        self._c.inc(amount, *self._key)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, *label_values: str) -> None:
        with self._lock:
            self._values[tuple(str(v) for v in label_values)] = float(value)

    def inc(self, amount: float = 1.0, *label_values: str) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, *label_values: str) -> None:
        self.inc(-amount, *label_values)

    def value(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(tuple(str(v) for v in label_values), 0.0)

    def values(self) -> Dict[Tuple[str, ...], float]:
        """Point-in-time snapshot of every label series (Registry.gather)."""
        with self._lock:
            return dict(self._values)

    def collect(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append("%s%s %s" % (self.name,
                                    _fmt_labels(self.label_names, key),
                                    _fmt_value(v)))
        return out


class Histogram(_Metric):
    kind = "histogram"

    DEFAULT_BUCKETS = exponential_buckets(0.001, 2, 15)

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = (),
                 buckets: Optional[Iterable[float]] = None, **kw):
        super().__init__(name, help, labels, **kw)
        self.buckets = sorted(buckets if buckets is not None
                              else self.DEFAULT_BUCKETS)
        # per label-key: mutable [per-bucket counts (NON-cumulative), sum, n].
        # observe() is on the per-pod scheduling path, so it does one bisect
        # + one increment; the cumulative form Prometheus exposes is computed
        # at collect/quantile time instead.
        self._series: Dict[Tuple[str, ...], List[Any]] = {}

    def observe(self, value: float, *label_values: str) -> None:
        for v in label_values:
            if type(v) is not str:
                label_values = tuple(str(x) for x in label_values)
                break
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            s = self._series.get(label_values)
            if s is None:
                s = self._series[label_values] = [[0] * len(self.buckets), 0.0, 0]
            if i < len(self.buckets):
                s[0][i] += 1
            s[1] += value
            s[2] += 1

    def observe_many(self, values: Sequence[float], *label_values: str) -> None:
        """Bulk observe under one lock (batch scheduling tail)."""
        if not values:
            return
        for v in label_values:
            if type(v) is not str:
                label_values = tuple(str(x) for x in label_values)
                break
        nb = len(self.buckets)
        with self._lock:
            s = self._series.get(label_values)
            if s is None:
                s = self._series[label_values] = [[0] * nb, 0.0, 0]
            counts = s[0]
            for value in values:
                i = bisect.bisect_left(self.buckets, value)
                if i < nb:
                    counts[i] += 1
            s[1] += sum(values)
            s[2] += len(values)

    def observe_array(self, values, *label_values: str) -> None:
        """Vectorized observe_many for numpy arrays (the timeline's
        per-pod decomposition feeds thousands of samples per wave; a
        per-value bisect there is the difference between a ≤5% and a
        ~15% armed-recording overhead).  Plain sequences fall through
        to observe_many."""
        try:
            import numpy as np
        except ImportError:
            self.observe_many(list(values), *label_values)
            return
        arr = np.asarray(values, dtype=float)
        if arr.size == 0:
            return
        for v in label_values:
            if type(v) is not str:
                label_values = tuple(str(x) for x in label_values)
                break
        nb = len(self.buckets)
        # side="left" matches observe_many's bisect_left exactly
        idx = np.searchsorted(self.buckets, arr, side="left")
        binc = np.bincount(idx[idx < nb], minlength=nb)
        total = float(arr.sum())
        with self._lock:
            s = self._series.get(label_values)
            if s is None:
                s = self._series[label_values] = [[0] * nb, 0.0, 0]
            counts = s[0]
            for j in binc.nonzero()[0]:
                counts[j] += int(binc[j])
            s[1] += total
            s[2] += int(arr.size)

    def labels(self, *label_values: str) -> "_BoundHistogram":
        return _BoundHistogram(self, tuple(str(v) for v in label_values))

    def count(self, *label_values: str) -> int:
        with self._lock:
            s = self._series.get(tuple(str(v) for v in label_values))
            return s[2] if s else 0

    def sum(self, *label_values: str) -> float:
        with self._lock:
            s = self._series.get(tuple(str(v) for v in label_values))
            return s[1] if s else 0.0

    def values(self) -> Dict[Tuple[str, ...], Tuple[int, float]]:
        """Point-in-time {labels: (count, sum)} snapshot (Registry.gather)."""
        with self._lock:
            return {k: (s[2], s[1]) for k, s in self._series.items()}

    def quantile(self, q: float, *label_values: str) -> float:
        """Approximate quantile from bucket upper bounds (for tests/latency
        reporting; Prometheus computes this server-side)."""
        with self._lock:
            s = self._series.get(tuple(str(v) for v in label_values))
            if not s or s[2] == 0:
                return 0.0
            counts, _, n = list(s[0]), s[1], s[2]
        target = q * n
        cum = 0
        for i, ub in enumerate(self.buckets):
            cum += counts[i]
            if cum >= target:
                return ub
        return float("inf")

    def collect(self) -> List[str]:
        with self._lock:
            items = sorted((k, (list(c), t, n))
                           for k, (c, t, n) in self._series.items())
        out = self._header()
        for key, (counts, total, n) in items:
            cum = 0
            for ub, c in zip(self.buckets, counts):
                cum += c
                out.append("%s_bucket%s %d" % (
                    self.name,
                    _fmt_labels(self.label_names + ("le",),
                                key + (_fmt_value(ub),)), cum))
            out.append("%s_bucket%s %d" % (
                self.name,
                _fmt_labels(self.label_names + ("le",), key + ("+Inf",)), n))
            out.append("%s_sum%s %s" % (
                self.name, _fmt_labels(self.label_names, key),
                _fmt_value(total)))
            out.append("%s_count%s %d" % (
                self.name, _fmt_labels(self.label_names, key), n))
        return out


class _BoundHistogram:
    __slots__ = ("_h", "_key")

    def __init__(self, h: Histogram, key: Tuple[str, ...]):
        self._h, self._key = h, key

    def observe(self, value: float) -> None:
        self._h.observe(value, *self._key)


class Registry:
    """A metrics registry; every binary holds one and serves it at /metrics.

    Mirrors component-base/metrics KubeRegistry: duplicate registration is
    an error; hidden (deprecated-past-window) metrics are skipped in
    exposition but keep accepting writes.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError("metric %r already registered" % metric.name)
            self._metrics[metric.name] = metric
        return metric

    def must_register(self, *metrics: _Metric) -> None:
        for m in metrics:
            self.register(m)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def expose(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            metrics = [m for _, m in sorted(self._metrics.items())]
        lines: List[str] = []
        for m in metrics:
            if m.hidden:
                continue
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"

    def gather(self) -> Dict[str, Dict[Tuple[str, ...], Any]]:
        """Structured snapshot: {metric_name: {label_key: value}} — counters
        and gauges yield floats, histograms (count, sum) pairs.  The
        programmatic twin of expose(), for tests and the seam dashboards
        (no text-format parsing)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, Dict[Tuple[str, ...], Any]] = {}
        for m in metrics:
            values = getattr(m, "values", None)
            if values is not None:
                out[m.name] = values()
        return out


# The default registry, shared across one process (legacyregistry analogue).
default_registry = Registry()


def new_counter(name, help="", labels=(), registry=None, **kw) -> Counter:
    return (registry or default_registry).register(
        Counter(name, help, labels, **kw))  # type: ignore[return-value]


def new_gauge(name, help="", labels=(), registry=None, **kw) -> Gauge:
    return (registry or default_registry).register(
        Gauge(name, help, labels, **kw))  # type: ignore[return-value]


def new_histogram(name, help="", labels=(), buckets=None,
                  registry=None, **kw) -> Histogram:
    return (registry or default_registry).register(
        Histogram(name, help, labels, buckets, **kw))  # type: ignore[return-value]
