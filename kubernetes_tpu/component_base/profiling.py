"""Continuous performance observatory: census core, host profiler, SLO
burn-rate tracker, and cross-process metrics federation.

The paper's success criteria (>=50k pods/s at p99 < 10ms, collective
bytes/wave cut >=4x under sharding) were, until this layer, verified by
one-off scripts (tools/collective_census.py, tools/profile_host.py)
whose output was hand-pasted into SCALING.md / LATENCY.md.  This module
turns each of those quantities into something the running system
observes about itself:

  * HLO collective census — a pure-regex walk over compiled-step HLO
    (no jax dependency at module level) counting every ICI collective
    with its tensor bytes and whether it sits inside the wave loop.
    Backends run it against their own lowered step functions at
    warmup/census time (`device_census()`); tools/collective_census.py
    is a thin CLI over the same code, so the committed
    `tpu_wave_collective_bytes` gauges and the offline tool agree
    bit-for-bit by construction.
  * HostProfiler — the sys._current_frames() sampling profiler lifted
    out of tools/profile_host.py into a bounded start/stop service with
    per-pipeline-stage host-time attribution
    (informer/submitter/resolver/binder) and collapsed-stacks output
    for the /debug/profile endpoints.
  * SLOTracker — rolling-window p50/p95/p99 scheduling latency against
    the 10 ms target with multi-window burn rates (SRE-style): the
    arm/disarm signal the adaptive overload-engagement path consumes.
  * Federation — aggregate per-instance metrics snapshots (structured
    Registry.gather() dicts or /metrics Prometheus text) into
    fleet-wide series for scale-out phase 2.

Everything here is off by default and wired up only through the
`profiling:` config stanza (scheduler/config.py) — an unconfigured
scheduler pays nothing.

Reference: staging/src/k8s.io/component-base/metrics (the stability-
levelled registry all of this exports through) and
pkg/scheduler/metrics/metrics.go:58 (the latency histograms whose 10 ms
SLO boundary the tracker mirrors); the /debug/profile endpoint follows
the net/http/pprof convention of serving profiler state next to
/metrics.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
from collections import Counter as _Counter
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# virtual-mesh bootstrap (shared by tools/, tests/conftest.py and the
# census CLI) — MUST run before the first jax import: the image's
# sitecustomize pins JAX_PLATFORMS=axon (the chip tunnel) and env vars
# alone don't stick, so the platform is also forced through jax.config.
# ---------------------------------------------------------------------------


def ensure_virtual_mesh(n_devices: int = 8):
    """Force an `n_devices`-way virtual CPU mesh and return the jax
    module.  Idempotent; safe to call when jax is already imported with
    the right platform (tests), in which case only the config update
    applies."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax


# ---------------------------------------------------------------------------
# HLO collective census core (jax-free: operates on HLO text)
# ---------------------------------------------------------------------------

DTYPE_BYTES = {"f32": 4, "s32": 4, "u32": 4, "bf16": 2, "f16": 2,
               "pred": 1, "s8": 1, "u8": 1, "f64": 8, "s64": 8, "u64": 8}

# The async `-start` forms (all-gather-start, reduce-scatter-start, ...)
# carry a (operand, result) tuple type; the matching `-done` ops are
# deliberately NOT matched so an async pair counts once.
COLLECTIVE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(.*?)\s*"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|"
    r"all-to-all)(-start)?\(", re.M)
SHAPE_RE = re.compile(r"(f32|s32|u32|bf16|f16|pred|s8|u8|f64|s64|u64)"
                      r"\[([\d,]*)\]")

_COMP_HEADER_RE = re.compile(r"^(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_CALL_RE = re.compile(r"(?:calls|to_apply|body|condition)=%?([\w.\-]+)")


def shape_bytes(type_str: str) -> int:
    total = 0
    for dt, dims in SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * DTYPE_BYTES[dt]
    return total


def census_from_hlo(hlo: str) -> dict:
    """Count every collective in an optimized-HLO module with its tensor
    bytes; collectives reachable from a while body run once PER WAVE.

    Returns {"collectives": {key: {op, count, bytes, per_wave}},
    "per_call_bytes": ..., "per_wave_bytes": ...} — the exact record
    tools/collective_census.py has always emitted (it now delegates
    here), so gauges derived from this match the tool bit-for-bit."""
    # split module into computations; while-loop bodies are separate
    # computations whose callers are while ops
    comps: dict[str, str] = {}
    cur = None
    for line in hlo.splitlines():
        # computation headers: "%name (params...) -> type {" — params may
        # contain nested parens (tuple types), so match only the prefix
        m = _COMP_HEADER_RE.match(line)
        if m and line.rstrip().endswith("{"):
            cur = m.group(1)
            comps[cur] = ""
        elif cur is not None:
            comps[cur] += line + "\n"
    while_bodies = set(re.findall(r"body=%?([\w.\-]+)", hlo))
    # transitively include computations called from while bodies
    frontier = set(while_bodies)
    in_loop: set[str] = set()
    while frontier:
        nxt = set()
        for name in frontier:
            if name in in_loop:
                continue
            in_loop.add(name)
            nxt |= set(_CALL_RE.findall(comps.get(name, "")))
        frontier = nxt - in_loop

    out: dict[str, dict] = {}
    for comp, body in comps.items():
        for m in COLLECTIVE_RE.finditer(body):
            out_type, op, started = m.group(1), m.group(2), m.group(3)
            if started:
                # async start: the tuple type is (operand, result); the
                # bytes moved are the result element (the last shape)
                shapes = SHAPE_RE.findall(out_type)
                b = 0
                if shapes:
                    dt, dims = shapes[-1]
                    n = 1
                    for d in dims.split(","):
                        if d:
                            n *= int(d)
                    b = n * DTYPE_BYTES[dt]
            else:
                b = shape_bytes(out_type)
            key = f"{op} {out_type.strip()}"
            rec = out.setdefault(key, {"op": op, "count": 0, "bytes": b,
                                       "per_wave": False})
            rec["count"] += 1
            if comp in in_loop:
                rec["per_wave"] = True
    return {"collectives": out,
            "per_call_bytes": sum(r["bytes"] * r["count"]
                                  for r in out.values()
                                  if not r["per_wave"]),
            "per_wave_bytes": sum(r["bytes"] * r["count"]
                                  for r in out.values() if r["per_wave"])}


def compiled_cost(compiled) -> dict:
    """XLA cost analysis of a compiled step (flops + bytes accessed, the
    HBM traffic proxy).  Best-effort: some backends return nothing."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # pragma: no cover - backend without cost analysis
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):  # pragma: no cover - exotic backend
        return {}
    return {"flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0)}


def census_lowered(lowered) -> dict:
    """Census one jax `Lowered` step: compile, walk the optimized HLO,
    attach the XLA cost analysis.  This is the single census entry point
    every backend and the offline tool share."""
    compiled = lowered.compile()
    rec = census_from_hlo(compiled.as_text())
    rec["cost"] = compiled_cost(compiled)
    return rec


def collective_bytes_by_op(rec: dict) -> tuple[dict, dict]:
    """Aggregate a census record into {op: bytes} sums for the per-wave
    and per-call collectives — the exact values the
    tpu_wave_collective_bytes / tpu_step_collective_bytes gauges carry."""
    per_wave: dict[str, int] = {}
    per_call: dict[str, int] = {}
    for r in rec.get("collectives", {}).values():
        dst = per_wave if r["per_wave"] else per_call
        dst[r["op"]] = dst.get(r["op"], 0) + r["bytes"] * r["count"]
    return per_wave, per_call


# ---------------------------------------------------------------------------
# host sampling profiler (lifted from tools/profile_host.py)
# ---------------------------------------------------------------------------

# thread-name -> pipeline stage.  Binder work happens inside the
# submitter/resolver threads, so it is carved out by frame (see
# _BINDER_FRAMES) before the thread mapping applies.
_STAGE_PATTERNS: tuple[tuple[str, str], ...] = (
    ("informer-", "informer"),
    ("bind", "binder"),          # ThreadPoolExecutor(thread_name_prefix="bind")
    ("sched-loop", "submitter"),
    ("wave-resolve", "resolver"),
    ("queue-flush", "queue"),
    ("apiserver", "apiserver"),
    ("tpu-worker", "device_worker"),
    ("MainThread", "main"),
)
_BINDER_FRAMES = frozenset({"_bulk_bind_commit", "_store_bind",
                            "bind_many", "_finish_batch",
                            # PR 13 decoupled binder: the _BinderWorker
                            # drain loop and its commit variants run on
                            # "binder<N>" threads, but a sample caught in
                            # a shared helper (binding_rows builds the
                            # wire rows, wait_on_permit blocks on the
                            # flow-control gate) must still attribute to
                            # binder work whatever thread it lands on
                            "_binding_cycle_turbo", "_binding_cycle_bulk",
                            "wait_on_permit", "binding_rows"})
# incremental flatten: the two halves of host-side tensor maintenance,
# carved out by frame like binder work.  Patch frames are checked FIRST —
# patch_node calls _encode_node, and an event patch should attribute to
# snapshot.patch even when the sample lands inside the shared encoder.
_PATCH_FRAMES = frozenset({"note_node_event", "patch_node", "patch_remove",
                           "compact", "_maybe_compact", "run_locked_node",
                           # PR 15 event-driven row maintenance: group-row
                           # release/probe and the namespace-mask row
                           # rewrite run only on the patch path
                           "_release_row", "_probe_bucket",
                           "_ns_mask_row_update"})
_FLATTEN_FRAMES = frozenset({"update_from_snapshot_tracked",
                             "_update_from_dirty", "_update_from_nodes_tracked",
                             "_sync_rows", "_encode_node",
                             "_encode_dynamic_bulk", "_encode_fresh_bulk",
                             # group registration also runs under
                             # patch_node, where the patch-first check
                             # order attributes it to snapshot.patch
                             "register_sg", "register_asg"})


def classify_stage(thread_name: str, co_names: Iterable[str]) -> str:
    """Map one sample (thread name + frame co_names, leaf first) onto a
    pipeline stage for scheduler_host_stage_seconds{stage}."""
    names = tuple(co_names)
    for co in names:
        if co in _BINDER_FRAMES:
            return "binder"
    for co in names:
        if co in _PATCH_FRAMES:
            return "snapshot.patch"
    for co in names:
        if co in _FLATTEN_FRAMES:
            return "snapshot.flatten"
    for prefix, stage in _STAGE_PATTERNS:
        if thread_name.startswith(prefix):
            return stage
    return "other"


def thread_cpu_seconds() -> dict:
    """Per-thread CPU seconds from /proc/self/task (utime+stime)."""
    out: dict[str, float] = {}
    base = "/proc/self/task"
    try:
        tids = os.listdir(base)
    except OSError:  # pragma: no cover - non-Linux
        return out
    for tid in tids:
        try:
            with open(f"{base}/{tid}/stat") as f:
                parts = f.read().rsplit(")", 1)[1].split()
            with open(f"{base}/{tid}/comm") as f:
                comm = f.read().strip()
            tick = os.sysconf("SC_CLK_TCK")
            out[f"{comm}-{tid}"] = round(
                (int(parts[11]) + int(parts[12])) / tick, 2)
        except (OSError, IndexError, ValueError):
            pass
    return out


class HostProfiler:
    """Always-on-capable sampling profiler over every Python thread.

    Python 3.12's cProfile holds the single global sys.monitoring slot,
    so per-thread deterministic profiling is impossible; this samples
    sys._current_frames() at ~200 Hz instead (low overhead, all
    threads, like py-spy).  Bounded: at most `max_stacks` distinct
    collapsed-stack keys are retained (overflow folds into a per-thread
    `<other>` bucket), so an arbitrarily long run holds constant memory.

    start()/stop() are idempotent; stop() joins the sampler thread so a
    stopped profiler leaves nothing running (pinned by
    tests/test_profiling.py)."""

    THREAD_NAME = "prof-sampler"

    def __init__(self, interval: float = 0.005, max_stacks: int = 512,
                 max_depth: int = 6):
        self.interval = interval
        self.max_stacks = max_stacks
        self.max_depth = max_depth
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._stacks: _Counter = _Counter()        # collapsed line -> samples
        self._stage_samples: _Counter = _Counter()  # stage -> samples
        self._stage_drained: dict[str, int] = {}    # stage -> samples drained
        self._samples_total = 0

    # -- lifecycle -------------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        with self._lock:
            if self.running:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=self.THREAD_NAME, daemon=True)
            self._thread.start()

    def stop(self, timeout: float = 1.0) -> bool:
        """Stop and join the sampler; returns True once the thread is
        gone."""
        with self._lock:
            t = self._thread
            self._stop.set()
        if t is not None:
            t.join(timeout)
            if t.is_alive():  # pragma: no cover - join timeout
                return False
        with self._lock:
            self._thread = None
        return True

    # -- sampling --------------------------------------------------------

    def _run(self) -> None:
        names: dict[int, str] = {}
        while not self._stop.is_set():
            for t in threading.enumerate():
                names[t.ident] = t.name
            self._sample_once(sys._current_frames(), names)
            time.sleep(self.interval)

    def _sample_once(self, frames: dict, names: dict) -> None:
        for ident, frame in frames.items():
            name = names.get(ident, str(ident))
            if name == self.THREAD_NAME:
                continue
            leaf = (f"{frame.f_code.co_name} "
                    f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}"
                    f":{frame.f_lineno}")
            # full co_name walk for stage classification (binder frames
            # can sit well above the leaf); repo-only frames, capped at
            # max_depth, for the collapsed stack
            parts: list[str] = []
            co_names: list[str] = []
            f = frame
            while f is not None:
                fn = f.f_code.co_filename
                co_names.append(f.f_code.co_name)
                if len(parts) < self.max_depth and (
                        "kubernetes_tpu" in fn or fn.endswith("bench.py")):
                    parts.append(f"{f.f_code.co_name}@{fn.rsplit('/', 1)[-1]}")
                f = f.f_back
            stage = classify_stage(name, co_names)
            # collapsed-stacks convention: root first, leaf last
            stack = ";".join([name] + list(reversed(parts))) if parts \
                else f"{name};{leaf.replace(' ', ':')}"
            with self._lock:
                self._samples_total += 1
                self._stage_samples[stage] += 1
                if stack in self._stacks or len(self._stacks) < self.max_stacks:
                    self._stacks[stack] += 1
                else:
                    self._stacks[f"{name};<other>"] += 1

    # -- views -----------------------------------------------------------

    def collapsed(self) -> str:
        """Collapsed-stacks text (Brendan Gregg format): one
        `frame;frame;...;frame count` line per distinct stack — the
        /debug/profile payload, flamegraph.pl-compatible."""
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
        return "\n".join(f"{stack} {n}" for stack, n in items) + (
            "\n" if items else "")

    def top_stacks(self, n: int = 5) -> list[tuple[str, int]]:
        with self._lock:
            return _Counter(self._stacks).most_common(n)

    def stage_seconds(self) -> dict[str, float]:
        """Cumulative per-stage host seconds (samples x interval)."""
        with self._lock:
            return {s: c * self.interval
                    for s, c in self._stage_samples.items()}

    def drain_stage_seconds(self) -> dict[str, float]:
        """Per-stage host-second DELTAS since the previous drain — the
        inc-only feed for the scheduler_host_stage_seconds counter (same
        drain discipline as the escape/shed tallies)."""
        out: dict[str, float] = {}
        with self._lock:
            for stage, c in self._stage_samples.items():
                d = c - self._stage_drained.get(stage, 0)
                if d > 0:
                    out[stage] = d * self.interval
                    self._stage_drained[stage] = c
        return out

    def samples_total(self) -> int:
        with self._lock:
            return self._samples_total

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._stage_samples.clear()
            self._stage_drained.clear()
            self._samples_total = 0


# The process-wide profiler behind /debug/profile on the apiserver and
# the device worker (tracing.default_tracer_provider analogue).
# Constructed idle; only the profiling: config stanza starts it.
default_host_profiler = HostProfiler()


# ---------------------------------------------------------------------------
# SLO tracker: rolling-window latency quantiles + multi-window burn rates
# ---------------------------------------------------------------------------


class SLOTracker:
    """Rolling-window scheduling-latency SLO accounting.

    Tracks submit->bind latencies against `target_ms` (the paper's
    10 ms p99 target) over multiple lookback windows and reports
    SRE-style burn rates: (fraction of observations over target) /
    (1 - objective).  A burn rate of 1.0 means the error budget is
    being consumed exactly at the sustainable rate; the multi-window
    AND (short window burning fast while a longer window confirms) is
    the standard page/arm signal and is exactly the engagement input
    the adaptive overload path needs."""

    def __init__(self, target_ms: float = 10.0, objective: float = 0.99,
                 windows: Sequence[float] = (60.0, 300.0, 3600.0),
                 max_samples: int = 16384, time_fn=time.monotonic):
        if not 0.0 < objective < 1.0:
            raise ValueError("objective must be in (0,1)")
        self.target_s = target_ms / 1000.0
        self.objective = objective
        self.windows = tuple(sorted(windows))
        self.max_samples = max_samples
        self._time = time_fn
        self._lock = threading.Lock()
        self._samples: deque[tuple[float, float]] = deque()  # (t, latency_s)

    def observe(self, latencies_s: Iterable[float],
                now: float | None = None) -> None:
        now = self._time() if now is None else now
        horizon = now - self.windows[-1]
        with self._lock:
            for lat in latencies_s:
                self._samples.append((now, lat))
            while self._samples and (self._samples[0][0] < horizon
                                     or len(self._samples) > self.max_samples):
                self._samples.popleft()

    def _window_samples(self, window: float | None,
                        now: float) -> list[float]:
        with self._lock:
            if window is None:
                return [lat for _, lat in self._samples]
            cutoff = now - window
            return [lat for t, lat in self._samples if t >= cutoff]

    def quantiles(self, window: float | None = None,
                  now: float | None = None) -> dict:
        """{"count", "p50_ms", "p95_ms", "p99_ms"} over the window (or
        the whole retained horizon)."""
        now = self._time() if now is None else now
        lats = sorted(self._window_samples(window, now))
        if not lats:
            return {"count": 0, "p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}

        def pct(q: float) -> float:
            i = min(len(lats) - 1, int(q * len(lats)))
            return lats[i] * 1000.0

        return {"count": len(lats), "p50_ms": pct(0.50),
                "p95_ms": pct(0.95), "p99_ms": pct(0.99)}

    def burn_rates(self, now: float | None = None) -> dict[str, float]:
        """{window_label: burn_rate}; labels are e.g. '60s', '3600s'."""
        now = self._time() if now is None else now
        budget = 1.0 - self.objective
        out: dict[str, float] = {}
        for w in self.windows:
            lats = self._window_samples(w, now)
            if not lats:
                out[f"{int(w)}s"] = 0.0
                continue
            over = sum(1 for lat in lats if lat > self.target_s)
            out[f"{int(w)}s"] = (over / len(lats)) / budget
        return out

    def breached(self, now: float | None = None) -> bool:
        """Multi-window arm signal: the two shortest windows BOTH burning
        faster than budget (fast burn confirmed by the slower window —
        a transient spike on the short window alone does not arm)."""
        rates = self.burn_rates(now)
        keys = [f"{int(w)}s" for w in self.windows[:2]]
        return all(rates.get(k, 0.0) > 1.0 for k in keys)


# ---------------------------------------------------------------------------
# cross-process metrics federation
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'[a-zA-Z_][a-zA-Z0-9_]*="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def parse_prometheus_text(text: str) -> Dict[str, Dict[Tuple[str, ...], float]]:
    """Parse /metrics exposition text into the same structured shape
    Registry.gather() returns for counters/gauges ({name: {label_values:
    value}}).  Histogram series surface as their _bucket/_sum/_count
    sample names (cumulative), which federate correctly by summation."""
    out: Dict[str, Dict[Tuple[str, ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels, raw = m.groups()
        try:
            value = float(raw)
        except ValueError:
            continue
        key = tuple(_unescape(v) for v in _LABEL_RE.findall(labels or ""))
        out.setdefault(name, {})[key] = value
    return out


def federate(snapshots: Iterable[Dict[str, Dict[Tuple[str, ...], Any]]]
             ) -> Dict[str, Dict[Tuple[str, ...], Any]]:
    """Merge per-instance metric snapshots (Registry.gather() dicts or
    parse_prometheus_text() results) into one fleet-wide view: counters
    and gauges sum per label series; histogram (count, sum) pairs sum
    elementwise.  An instance that died mid-window simply contributes
    its last snapshot — counters are monotone per instance, so the
    federated total never goes backwards as long as callers snapshot
    before discarding an instance (bench.py run_scaleout does)."""
    out: Dict[str, Dict[Tuple[str, ...], Any]] = {}
    for snap in snapshots:
        for name, series in snap.items():
            dst = out.setdefault(name, {})
            for key, val in series.items():
                if isinstance(val, tuple):
                    c, s = dst.get(key, (0, 0.0))
                    dst[key] = (c + val[0], s + val[1])
                else:
                    dst[key] = dst.get(key, 0.0) + val
    return out


def federate_texts(texts: Iterable[str]
                   ) -> Dict[str, Dict[Tuple[str, ...], float]]:
    """Federation over raw per-instance /metrics exposition bodies — the
    true cross-process path (scale-out phase 2: one HTTP pull per
    instance, one merged view)."""
    return federate(parse_prometheus_text(t) for t in texts)
