"""Wave timeline: overlap-aware stage intervals for the batch pipeline.

Reference: staging/src/k8s.io/component-base/tracing (the span layer
this rides next to) and the scheduler's utiltrace usage at
pkg/scheduler/schedule_one.go — but where utiltrace logs slow-path
step durations, this module keeps interval SETS, because the quantity
the paper's pipelining argument needs (device idle share) is a union
measure no per-step duration sum can express.

The PR 8 observatory samples stacks and sums per-stage seconds — a
*duration* view that cannot distinguish "device busy 40% of the wall
clock" from "device busy 40% of the time the host happened to also be
busy".  This module records every pipeline stage as an INTERVAL
``(wave_id, stage, t_start, t_end, thread)`` in a bounded per-process
ring, so the committed metrics are computed from interval set algebra:

- ``scheduler_wave_device_idle_share`` — the wall-clock fraction where
  NO device stage (h2d / device-step / d2h) is in flight, computed by
  interval UNION.  ``1 - Σ stage_seconds / wall`` double-counts the
  moment two stages overlap and goes wrong the instant the pipeline
  PR lands; the union form stays correct under depth-N pipelining.
- per-stage overlap ratios — for each stage, the fraction of its own
  busy time during which at least one OTHER stage is also in flight
  (0.0 = fully serial pipeline, → 1.0 = fully overlapped).
- per-pod e2e decomposition — enqueue → dispatch → batch-form →
  device → resolve → bind-commit wall boundaries, telescoped so the
  segment sum equals the measured e2e by construction, plus a watch
  segment stitched in post-hoc from bind-ledger observation times.

Everything is off by default (``profiling.timeline``) and the armed
overhead is pinned ≤5% by a bench A/B (tests/test_timeline.py).

Clock discipline: callers pass ``time.monotonic()`` pairs; the ring
stores wall-anchored seconds (``wall = mono + anchor`` with the anchor
captured once per reset — the same wall-anchoring trick tracing.Span
uses), so intervals from different processes merge by concatenation
once each process anchored its own ring (the PR 2 traceparent offset
handshake gives the remote seam the same property for worker spans).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import tracing

# The eight pipeline stages, in wave order.  Stage names are the
# vocabulary shared by the ring, /debug/timeline, bench rows and the
# README "Wave timeline" section — add here first.
STAGES = ("event-drain", "patch", "batch-form", "h2d",
          "device-step", "d2h", "resolve", "bind-commit")

# Stages during which the device is (or may be) doing work: the idle
# share is 1 - union(these)/wall.  Host-only stages deliberately
# excluded — a host stage overlapping a device stage is the GOAL.
DEVICE_STAGES = frozenset({"h2d", "device-step", "d2h"})

# Per-pod decomposition segments, in telescoped order.  queue+form+
# device+resolve+bind sum to the bind-visible e2e exactly; watch is
# stitched in afterwards from ledger observation timestamps.
POD_SEGMENTS = ("queue", "form", "device", "resolve", "bind", "watch")


def derive_segment_cols(t_enq, t_bind: float, marks) -> Dict[str, Any]:
    """Telescoped per-pod decomposition columns from raw wave inputs.

    ``marks`` is ``(form_start, form_end, device_end, resolve_end)``
    wall seconds (any entry may be None when that stage didn't run).
    Boundaries are clamped monotone non-decreasing into
    ``[t_enq, t_bind]``, so every segment is >= 0 and the segments of
    one pod sum EXACTLY to its bind-visible e2e.  This runs at READ
    time (pods() views, segment summaries) — the bind hot path records
    only the raw block, which is what keeps the armed overhead inside
    the ≤5% pin."""
    f0, f1, dev, res = marks
    try:
        import numpy as np
    except ImportError:
        np = None
    if np is not None:
        t = np.asarray(t_enq, np.float64)
        b_disp = np.minimum(t_bind, np.maximum(t, f0)) \
            if f0 is not None else np.minimum(t_bind, t)
        b_form = np.minimum(t_bind, np.maximum(b_disp, f1)) \
            if f1 is not None else b_disp
        b_dev = np.minimum(t_bind, np.maximum(b_form, dev)) \
            if dev is not None else b_form
        b_res = np.minimum(t_bind, np.maximum(b_dev, res)) \
            if res is not None else b_dev
        return {"queue": (b_disp - t) * 1e3,
                "form": (b_form - b_disp) * 1e3,
                "device": (b_dev - b_form) * 1e3,
                "resolve": (b_res - b_dev) * 1e3,
                "bind": (t_bind - b_res) * 1e3,
                "watch": np.zeros(len(t))}
    cols: Dict[str, Any] = {s: [] for s in POD_SEGMENTS}
    for te in t_enq:
        b_disp = min(t_bind, max(te, f0)) if f0 is not None \
            else min(t_bind, te)
        b_form = min(t_bind, max(b_disp, f1)) if f1 is not None else b_disp
        b_dev = min(t_bind, max(b_form, dev)) if dev is not None else b_form
        b_res = min(t_bind, max(b_dev, res)) if res is not None else b_dev
        cols["queue"].append((b_disp - te) * 1e3)
        cols["form"].append((b_form - b_disp) * 1e3)
        cols["device"].append((b_dev - b_form) * 1e3)
        cols["resolve"].append((b_res - b_dev) * 1e3)
        cols["bind"].append((t_bind - b_res) * 1e3)
        cols["watch"].append(0.0)
    return cols


# -- interval set algebra ---------------------------------------------------


def _merged(pairs: Iterable[Tuple[float, float]]) -> List[List[float]]:
    """Sorted, disjoint segments covering the union of ``pairs``."""
    out: List[List[float]] = []
    for t0, t1 in sorted(p for p in pairs if p[1] > p[0]):
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1][1] = t1
        else:
            out.append([t0, t1])
    return out


def interval_union(pairs: Iterable[Tuple[float, float]]) -> float:
    """Total measure of the union of ``(t0, t1)`` pairs.  Overlapping
    and nested intervals count once — the whole point."""
    return sum(hi - lo for lo, hi in _merged(pairs))


def _intersect_measure(a: List[List[float]], b: List[List[float]]) -> float:
    """Measure of the intersection of two merged segment lists."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def device_idle_share(intervals: Iterable[Dict[str, Any]],
                      window: Optional[Tuple[float, float]] = None,
                      ) -> Optional[float]:
    """Wall-clock fraction of ``window`` with no device stage in
    flight, by interval union (NOT ``1 - Σ durations / wall``, which
    double-counts overlap and would report negative idle the moment
    h2d for wave N+1 overlaps device-step for wave N).

    ``window`` defaults to the observed extent of ALL intervals (host
    stages included — host-only head/tail time is honestly idle).
    Returns None when there is nothing to measure."""
    rows = list(intervals)
    if window is None:
        if not rows:
            return None
        w0 = min(r["t0_unix_s"] for r in rows)
        w1 = max(r["t1_unix_s"] for r in rows)
    else:
        w0, w1 = window
    span = w1 - w0
    if span <= 0:
        return None
    busy = interval_union(
        (max(r["t0_unix_s"], w0), min(r["t1_unix_s"], w1))
        for r in rows if r["stage"] in DEVICE_STAGES)
    return max(0.0, min(1.0, 1.0 - busy / span))


def overlap_ratios(intervals: Iterable[Dict[str, Any]],
                   ) -> Dict[str, float]:
    """Per stage: the fraction of that stage's OWN union time during
    which at least one interval of any OTHER stage is in flight.
    A fully serial pipeline scores 0.0 everywhere; the double-buffered
    pipeline should drive device-step's ratio toward 1.0."""
    by_stage: Dict[str, List[Tuple[float, float]]] = {}
    for r in intervals:
        by_stage.setdefault(r["stage"], []).append(
            (r["t0_unix_s"], r["t1_unix_s"]))
    out: Dict[str, float] = {}
    for stage, pairs in by_stage.items():
        own = _merged(pairs)
        own_t = sum(hi - lo for lo, hi in own)
        if own_t <= 0:
            out[stage] = 0.0
            continue
        others = _merged(p for s2, ps in by_stage.items()
                         if s2 != stage for p in ps)
        out[stage] = min(1.0, _intersect_measure(own, others) / own_t)
    return out


def stitch_watch_segments(pod_rows: Iterable[Dict[str, Any]],
                          observed_at: Dict[str, float],
                          ) -> List[Dict[str, Any]]:
    """Backfill the ``watch`` segment from external observation times
    (``{pod_key: wall_s}`` — e.g. a WireBindLedger tailing the
    apiserver watch), re-summing e2e so the telescoping invariant
    (segments sum to e2e) survives the stitch."""
    out = []
    for row in pod_rows:
        row = dict(row)
        seg = dict(row["segments_ms"])
        obs = observed_at.get(row["key"])
        t_bind = row.get("t_bind_unix_s")
        if obs is not None and t_bind is not None and obs > t_bind:
            seg["watch"] = (obs - t_bind) * 1e3
        row["segments_ms"] = seg
        row["e2e_ms"] = sum(seg.values())
        out.append(row)
    return out


# -- the recorder -----------------------------------------------------------


class _StageToken:
    """Handle from Timeline.begin(); ends the interval on exit (the
    context-manager form the timeline-stage-paired lint rule checks
    for).  A shared inert instance stands in when recording is off so
    the disabled path allocates nothing."""

    __slots__ = ("tl", "stage_name", "wave", "t0")

    def __init__(self, tl: Optional["Timeline"], stage_name: str,
                 wave: Optional[int], t0: float):
        self.tl = tl
        self.stage_name = stage_name
        self.wave = wave
        self.t0 = t0

    def __enter__(self) -> "_StageToken":
        return self

    def __exit__(self, *exc) -> None:
        if self.tl is not None:
            self.tl.end(self)


_NULL_TOKEN = _StageToken(None, "", None, 0.0)

# Shared inert context manager for call sites whose Timeline may be
# entirely absent (scheduler._tl_stage): entering/exiting is a no-op.
NULL_STAGE = _NULL_TOKEN


class _WaveScope:
    __slots__ = ("tl", "wave", "prev")

    def __init__(self, tl: "Timeline", wave: Optional[int]):
        self.tl = tl
        self.wave = wave

    def __enter__(self) -> "_WaveScope":
        self.prev = getattr(self.tl._tls, "wave", None)
        self.tl._tls.wave = self.wave
        return self

    def __exit__(self, *exc) -> None:
        self.tl._tls.wave = self.prev


class Timeline:
    """Bounded per-process interval ring plus derived views.

    Cheap when disabled: every hot-path call is guarded by one
    attribute read (``if tl.enabled``) and the begin/stage fast paths
    return a shared inert token.  When enabled, a commit is one lock
    acquire, one deque append and one per-wave min/max merge."""

    MAX_WAVE_MARKS = 512

    def __init__(self, ring: int = 4096, pod_ring: int = 4096,
                 enabled: bool = False, proc: str = "scheduler"):
        self.enabled = enabled
        self.proc = proc
        self._ring = ring
        self._pod_ring = pod_ring
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            # wall = mono + anchor; captured once so every interval in
            # this ring shares one consistent clock mapping
            self._anchor = time.time() - time.monotonic()
            self._rows: deque = deque(maxlen=self._ring)
            # per-wave column blocks (keys, wave, t_enq_seq, t_bind,
            # {segment: ms_seq}); bounded by total pod count, evicted
            # block-at-a-time (the bind-commit path appends one block
            # per wave instead of one row per pod)
            self._pods: deque = deque()
            self._pod_n = 0
            self._marks: Dict[Any, Dict[str, List[float]]] = {}

    def configure(self, enabled: Optional[bool] = None,
                  ring: Optional[int] = None,
                  pod_ring: Optional[int] = None,
                  proc: Optional[str] = None) -> None:
        """Apply a profiling: stanza to the live (import-time) default
        instance; resizing re-arms the ring."""
        if proc is not None:
            self.proc = proc
        resize = ((ring is not None and ring != self._ring)
                  or (pod_ring is not None and pod_ring != self._pod_ring))
        if ring is not None:
            self._ring = ring
        if pod_ring is not None:
            self._pod_ring = pod_ring
        if resize:
            self.reset()
        if enabled is not None:
            self.enabled = enabled

    # -- clock --------------------------------------------------------------

    def wall(self, t_mono: float) -> float:
        """Map a time.monotonic() reading onto this ring's wall clock
        (the same anchor every committed interval used)."""
        return t_mono + self._anchor

    # -- recording ----------------------------------------------------------

    def current_wave(self) -> Optional[int]:
        return getattr(self._tls, "wave", None)

    def use_wave(self, wave: Optional[int]) -> _WaveScope:
        """Thread-local current-wave scope (mirrors tracing.use_span):
        backends record intervals against the dispatching wave without
        widening dispatch() signatures across the backend ladder."""
        return _WaveScope(self, wave)

    def begin(self, stage_name: str,
              wave: Optional[int] = None) -> _StageToken:
        if not self.enabled:
            return _NULL_TOKEN
        if wave is None:
            wave = self.current_wave()
        return _StageToken(self, stage_name, wave, time.monotonic())

    def end(self, token: _StageToken) -> None:
        if token.tl is None or not self.enabled:
            return
        self.record(token.stage_name, token.t0, time.monotonic(),
                    wave=token.wave)

    def stage(self, stage_name: str,
              wave: Optional[int] = None) -> _StageToken:
        """``with tl.stage("resolve", wave=cycle):`` — the common form."""
        return self.begin(stage_name, wave=wave)

    def record(self, stage_name: str, t0: float, t1: float,
               wave: Optional[int] = None) -> None:
        """Commit an interval from a time.monotonic() pair.  The
        retroactive form — for intervals whose endpoints live on
        opposite sides of a closure boundary (dispatch vs resolve),
        where a begin token cannot travel."""
        if not self.enabled or t1 < t0:
            return
        if wave is None:
            wave = self.current_wave()
        thread = threading.current_thread().name
        with self._lock:
            w0 = t0 + self._anchor
            w1 = t1 + self._anchor
            self._rows.append((stage_name, wave, w0, w1, thread, self.proc))
            if wave is not None:
                m = self._marks.get(wave)
                if m is None:
                    m = self._marks[wave] = {}
                    while len(self._marks) > self.MAX_WAVE_MARKS:
                        self._marks.pop(next(iter(self._marks)))
                span = m.get(stage_name)
                if span is None:
                    m[stage_name] = [w0, w1]
                else:
                    if w0 < span[0]:
                        span[0] = w0
                    if w1 > span[1]:
                        span[1] = w1

    def ingest(self, rows: Iterable[Dict[str, Any]]) -> int:
        """Merge already-wall-anchored interval dicts from another
        process (remote device worker over the seam, procrun children
        into the supervisor).  Returns the count merged."""
        n = 0
        with self._lock:
            for r in rows:
                self._rows.append((r["stage"], r.get("wave"),
                                   float(r["t0_unix_s"]),
                                   float(r["t1_unix_s"]),
                                   r.get("thread", "?"),
                                   r.get("proc", "?")))
                n += 1
        return n

    def record_pod(self, key: str, segments_ms: Dict[str, float],
                   t_enqueue_wall: float, t_bind_wall: float,
                   wave: Optional[int] = None) -> None:
        self.record_pod_block(
            [key], wave, [t_enqueue_wall], t_bind_wall,
            {s: [float(segments_ms.get(s, 0.0))] for s in POD_SEGMENTS})

    def record_pod_block(self, keys: List[str], wave: Optional[int],
                         t_enq, t_bind_wall: float,
                         seg_cols: Optional[Dict[str, Any]] = None,
                         marks: Optional[Tuple] = None) -> None:
        """Column form of record_pod for the bind-commit hot path: one
        append and one lock round per WAVE, not per pod.  ``t_enq`` and
        each ``seg_cols[segment]`` are sequences (list or numpy array)
        aligned with ``keys``; values are wall seconds / milliseconds.
        Callers on the hot path pass ``marks`` — the raw
        ``(form_start, form_end, device_end, resolve_end)`` wave marks
        — instead of ``seg_cols``; the telescoped decomposition is then
        derived lazily by pods() (derive_segment_cols), so arming adds
        only this append to the bind path.  The ring bound counts pods,
        evicting whole blocks oldest-first (an oversized single block
        keeps its newest ``pod_ring`` rows)."""
        if not self.enabled or not len(keys):
            return
        with self._lock:
            self._pods.append((keys, wave, t_enq, t_bind_wall,
                               seg_cols, marks))
            self._pod_n += len(keys)
            while self._pod_n > self._pod_ring and len(self._pods) > 1:
                old = self._pods.popleft()
                self._pod_n -= len(old[0])
            if self._pod_n > self._pod_ring:
                k, w, te, tb, cols, mk = self._pods[0]
                keep = self._pod_ring
                self._pods[0] = (k[-keep:], w, te[-keep:], tb,
                                 None if cols is None else
                                 {s: c[-keep:] for s, c in cols.items()},
                                 mk)
                self._pod_n = keep

    # -- views --------------------------------------------------------------

    def intervals(self, drain: bool = False) -> List[Dict[str, Any]]:
        with self._lock:
            rows = list(self._rows)
            if drain:
                self._rows.clear()
        return [{"stage": s, "wave": w, "t0_unix_s": t0, "t1_unix_s": t1,
                 "thread": thr, "proc": proc}
                for s, w, t0, t1, thr, proc in rows]

    def pods(self, drain: bool = False) -> List[Dict[str, Any]]:
        with self._lock:
            blocks = list(self._pods)
            if drain:
                self._pods.clear()
                self._pod_n = 0
        out: List[Dict[str, Any]] = []
        for keys, wave, t_enq, t_bind, cols, marks in blocks:
            if cols is None:
                cols = derive_segment_cols(t_enq, t_bind,
                                           marks or (None,) * 4)
            colseq = [cols.get(s) for s in POD_SEGMENTS]
            for i, key in enumerate(keys):
                segs = {s: (float(c[i]) if c is not None else 0.0)
                        for s, c in zip(POD_SEGMENTS, colseq)}
                out.append({"key": key, "wave": wave,
                            "t_enqueue_unix_s": float(t_enq[i]),
                            "t_bind_unix_s": float(t_bind),
                            "segments_ms": segs,
                            "e2e_ms": sum(segs.values())})
        return out

    def wave_marks(self, wave: Any) -> Dict[str, Tuple[float, float]]:
        """Per-stage merged (first-start, last-end) wall bounds for one
        wave — the boundary timestamps the pod decomposition telescopes
        between."""
        with self._lock:
            m = self._marks.get(wave) or {}
            return {s: (b[0], b[1]) for s, b in m.items()}

    def snapshot_summary(self, window_s: Optional[float] = None,
                         ) -> Dict[str, Any]:
        rows = self.intervals()
        if window_s is not None and rows:
            w1 = max(r["t1_unix_s"] for r in rows)
            rows = [r for r in rows if r["t1_unix_s"] >= w1 - window_s]
        counts: Dict[str, int] = {}
        for r in rows:
            counts[r["stage"]] = counts.get(r["stage"], 0) + 1
        return {
            "proc": self.proc,
            "intervals": len(rows),
            "stages": counts,
            "device_idle_share": device_idle_share(rows),
            "overlap": overlap_ratios(rows),
        }

    def debug_json(self) -> str:
        """The /debug/timeline body: summary + raw intervals + pod
        decomposition rows (the Chrome form is served separately)."""
        return json.dumps({
            "enabled": self.enabled,
            **self.snapshot_summary(),
            "interval_rows": self.intervals(),
            "pods": self.pods(),
        }, indent=1)

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Perfetto-loadable Chrome trace-event document: one pid lane
        per recording process, one named tid lane per thread (via the
        shared metadata-aware writer, satellite of PR 2)."""
        events: List[Dict[str, Any]] = []
        pids: Dict[str, int] = {}
        tids: Dict[Tuple[int, str], int] = {}
        for r in self.intervals():
            pid = pids.setdefault(r["proc"], len(pids) + 1)
            tid = tids.setdefault((pid, r["thread"]), len(tids) + 1)
            events.append({
                "name": r["stage"], "ph": "X", "cat": "timeline",
                "ts": r["t0_unix_s"] * 1e6,
                "dur": max(r["t1_unix_s"] - r["t0_unix_s"], 0.0) * 1e6,
                "pid": pid, "tid": tid,
                "args": {"wave": r["wave"]},
            })
        return tracing.chrome_trace_doc(
            events,
            {pid: name for name, pid in pids.items()},
            {(pid, tid): thr for (pid, thr), tid in tids.items()})


# process-local: per-process interval ring — each OS process (scheduler
# child, device worker) anchors and fills its own; cross-process views
# merge via ingest()/federation, never via shared memory.
default_timeline = Timeline()
