"""Operation tracing.

Two layers, mirroring the reference:

1. ``Trace`` — utiltrace-style (k8s.io/utils/trace, used by the scheduler
   at schedule_one.go:373: a named operation accumulates steps and is
   logged only if total latency exceeds a threshold).
2. ``TracerProvider``/``Span`` — a minimal OTel-shaped provider
   (component-base/tracing/utils.go:35 NewProvider) with an in-memory
   exporter, so the apiserver WithTracing filter and kubelet CRI wrapping
   (KubeletTracing gate) have a seam.  On TPU the heavyweight profiling
   story is jax.profiler (see ops/backend.py), not OTel; this keeps the
   control-plane contract.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class Trace:
    """utiltrace.Trace: log steps when an operation exceeds a threshold."""

    def __init__(self, name: str, **fields: Any):
        self.name = name
        self.fields = fields
        self.start = time.monotonic()
        self.steps: List[tuple] = []

    def step(self, msg: str, **fields: Any) -> None:
        self.steps.append((time.monotonic(), msg, fields))

    def log_if_long(self, threshold: float) -> bool:
        total = time.monotonic() - self.start
        if total < threshold:
            return False
        parts = ["Trace %r (total %.1fms):" % (self.name, total * 1e3)]
        last = self.start
        for t, msg, fields in self.steps:
            extra = (" " + ",".join("%s=%s" % kv for kv in fields.items())
                     if fields else "")
            parts.append("  step %r +%.1fms%s" % (msg, (t - last) * 1e3, extra))
            last = t
        logger.info("\n".join(parts))
        return True


class Span:
    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional["Span"] = None):
        self.tracer = tracer
        self.name = name
        self.parent = parent
        self.attributes: Dict[str, Any] = {}
        self.events: List[tuple] = []
        self.start_time = time.monotonic()
        self.end_time: Optional[float] = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attrs: Any) -> None:
        self.events.append((time.monotonic(), name, attrs))

    def end(self) -> None:
        if self.end_time is None:
            self.end_time = time.monotonic()
            self.tracer.provider._export(self)

    @property
    def duration(self) -> float:
        return (self.end_time or time.monotonic()) - self.start_time

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class Tracer:
    def __init__(self, provider: "TracerProvider", name: str):
        self.provider = provider
        self.name = name

    def start_span(self, name: str, parent: Optional[Span] = None) -> Span:
        return Span(self, name, parent)


class TracerProvider:
    """In-memory provider; sampling_rate mirrors TracingConfiguration
    SamplingRatePerMillion (0 disables record-keeping but spans still
    function as timers)."""

    def __init__(self, sampling_rate_per_million: int = 1_000_000,
                 max_spans: int = 4096):
        self.sampling_rate_per_million = sampling_rate_per_million
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self.spans: List[Span] = []
        self._counter = 0

    def tracer(self, name: str) -> Tracer:
        return Tracer(self, name)

    def _export(self, span: Span) -> None:
        with self._lock:
            self._counter += 1
            keep = (self._counter * self.sampling_rate_per_million
                    ) % 1_000_000 < self.sampling_rate_per_million
            if self.sampling_rate_per_million >= 1_000_000:
                keep = True
            elif self.sampling_rate_per_million <= 0:
                keep = False
            if keep:
                self.spans.append(span)
                if len(self.spans) > self.max_spans:
                    del self.spans[: len(self.spans) - self.max_spans]

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self.spans)


default_tracer_provider = TracerProvider()
