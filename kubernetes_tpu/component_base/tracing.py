"""Operation tracing.

Two layers, mirroring the reference:

1. ``Trace`` — utiltrace-style (k8s.io/utils/trace, used by the scheduler
   at schedule_one.go:373: a named operation accumulates steps and is
   logged only if total latency exceeds a threshold).
2. ``TracerProvider``/``Span`` — a minimal OTel-shaped provider
   (component-base/tracing/utils.go:35 NewProvider) with an in-memory
   exporter, so the apiserver WithTracing filter and kubelet CRI wrapping
   (KubeletTracing gate) have a seam.  On TPU the heavyweight profiling
   story is jax.profiler (see ops/backend.py), not OTel; this keeps the
   control-plane contract.

The span layer carries W3C trace context (``traceparent``,
https://www.w3.org/TR/trace-context/) so spans opened on a remote device
worker (ops/remote.py) parent into the scheduler-side batch trace, and
head sampling mirrors TracingConfiguration.SamplingRatePerMillion
(component-base/apis/v1: the KEP-647 stanza): the decision is made once
at the ROOT span and inherited by children/remote spans, so a trace is
never torn.  Exported spans land in a bounded in-memory ring grouped by
trace (the flight recorder served at /debug/traces) and can be dumped as
Chrome trace-event JSON (Perfetto-loadable) via ``to_chrome_trace``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class Trace:
    """utiltrace.Trace: log steps when an operation exceeds a threshold."""

    def __init__(self, name: str, **fields: Any):
        self.name = name
        self.fields = fields
        self.start = time.monotonic()
        self.steps: List[tuple] = []

    def step(self, msg: str, **fields: Any) -> None:
        self.steps.append((time.monotonic(), msg, fields))

    def log_if_long(self, threshold: float) -> bool:
        total = time.monotonic() - self.start
        if total < threshold:
            return False
        parts = ["Trace %r (total %.1fms):" % (self.name, total * 1e3)]
        last = self.start
        for t, msg, fields in self.steps:
            extra = (" " + ",".join("%s=%s" % kv for kv in fields.items())
                     if fields else "")
            parts.append("  step %r +%.1fms%s" % (msg, (t - last) * 1e3, extra))
            last = t
        logger.info("\n".join(parts))
        return True


# -- W3C trace context -----------------------------------------------------

class SpanContext:
    """The propagated identity of a span: what crosses a process boundary.

    Mirrors OTel SpanContext / the W3C traceparent triple: 128-bit trace
    id, 64-bit span id, and the sampled flag (the head-sampling decision
    travels WITH the context so a remote worker never re-samples)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "SpanContext(%s, %s, sampled=%s)" % (
            self.trace_id, self.span_id, self.sampled)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def format_traceparent(ctx: SpanContext) -> str:
    """``00-<trace-id>-<parent-id>-<flags>`` (trace-context section 3.2)."""
    return "00-%s-%s-%s" % (ctx.trace_id, ctx.span_id,
                            "01" if ctx.sampled else "00")


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C traceparent header; None on anything malformed (an
    unparseable header MUST NOT fail the request — the span is simply
    unparented, per spec section 4)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if (len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16
            or len(flags) != 2):
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id, sampled=bool(int(flags, 16) & 1))


class Span:
    """One timed operation.  Wall-clock start (time.time()) anchors the
    span on a cross-process timeline (Chrome trace alignment between
    scheduler and worker); the monotonic pair measures duration."""

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional["Span"] = None,
                 context: Optional[SpanContext] = None,
                 start: Optional[float] = None):
        self.tracer = tracer
        self.name = name
        self.parent = parent
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_span_id = parent.span_id
            self.sampled: Optional[bool] = parent.sampled
        elif context is not None:  # remote parent (propagated traceparent)
            self.trace_id = context.trace_id
            self.parent_span_id = context.span_id
            self.sampled = context.sampled
        else:
            self.trace_id = new_trace_id()
            self.parent_span_id = None
            # root: head-sampling decision now, inherited by children
            self.sampled = tracer.provider._sample()
        self.span_id = new_span_id()
        self.attributes: Dict[str, Any] = {}
        self.events: List[tuple] = []
        # recording thread, captured at creation: the Chrome export
        # names one tid lane per (process, thread) so Perfetto groups
        # scheduler-loop vs binder-worker vs server threads
        self.thread = threading.current_thread().name
        now = time.monotonic()
        self.start_time = start if start is not None else now
        # wall anchor back-dated by the same monotonic offset
        self.start_wall = time.time() - (now - self.start_time)
        self.end_time: Optional[float] = None

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id,
                           sampled=bool(self.sampled))

    def traceparent(self) -> str:
        return format_traceparent(self.context)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attrs: Any) -> None:
        self.events.append((time.monotonic(), name, attrs))

    def end(self, end: Optional[float] = None) -> None:
        if self.end_time is None:
            self.end_time = end if end is not None else time.monotonic()
            self.tracer.provider._export(self)

    @property
    def duration(self) -> float:
        return (self.end_time or time.monotonic()) - self.start_time

    def to_dict(self) -> Dict[str, Any]:
        """JSON-friendly view (the /debug/traces wire shape)."""
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "start_unix_s": round(self.start_wall, 6),
            "duration_s": round(self.duration, 6),
            "attributes": dict(self.attributes),
            "events": [{"name": n, "offset_s": round(t - self.start_time, 6),
                        **({"attributes": a} if a else {})}
                       for t, n, a in self.events],
        }

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class Tracer:
    def __init__(self, provider: "TracerProvider", name: str):
        self.provider = provider
        self.name = name

    def start_span(self, name: str, parent: Optional[Span] = None,
                   context: Optional[SpanContext] = None,
                   start: Optional[float] = None) -> Span:
        return Span(self, name, parent=parent, context=context, start=start)


class TracerProvider:
    """In-memory provider; sampling_rate mirrors TracingConfiguration
    SamplingRatePerMillion (0 disables record-keeping but spans still
    function as timers).

    Exported spans feed two bounded stores: ``spans`` (flat, newest
    ``max_spans``) and a per-trace flight-recorder ring (newest
    ``max_traces`` traces, served at /debug/traces)."""

    def __init__(self, sampling_rate_per_million: int = 1_000_000,
                 max_spans: int = 4096, max_traces: int = 256):
        self.sampling_rate_per_million = sampling_rate_per_million
        self.max_spans = max_spans
        self.max_traces = max_traces
        self._lock = threading.Lock()
        self.spans: List[Span] = []
        self._traces: "OrderedDict[str, List[Span]]" = OrderedDict()
        self._counter = 0

    def tracer(self, name: str) -> Tracer:
        return Tracer(self, name)

    def configure(self, sampling_rate_per_million: Optional[int] = None,
                  max_spans: Optional[int] = None,
                  max_traces: Optional[int] = None) -> None:
        """Apply a tracing: config stanza to a live provider (the shared
        default provider is created at import, before config loads)."""
        with self._lock:
            if sampling_rate_per_million is not None:
                self.sampling_rate_per_million = sampling_rate_per_million
            if max_spans is not None:
                self.max_spans = max_spans
            if max_traces is not None:
                self.max_traces = max_traces

    def _sample(self) -> bool:
        """Head-sampling decision for a new root span.

        Counter-proportional: root k is kept exactly when the running
        product k*rate crosses the next multiple of one million, so any
        window of n roots keeps n*rate/1e6 +- 1 of them.  (The previous
        modulo form compared (k*rate) % 1e6 against the rate itself,
        which keeps a fraction unrelated to rate/1e6 for intermediate
        rates — e.g. rate 600_000 kept every root.)"""
        rate = self.sampling_rate_per_million
        if rate >= 1_000_000:
            return True
        if rate <= 0:
            return False
        with self._lock:
            self._counter += 1
            c = self._counter
        return (c * rate) // 1_000_000 > ((c - 1) * rate) // 1_000_000

    def _export(self, span: Span) -> None:
        if span.sampled is None:  # bare Span() never given a decision
            span.sampled = self._sample()
        if not span.sampled:
            return
        with self._lock:
            self.spans.append(span)
            if len(self.spans) > self.max_spans:
                del self.spans[: len(self.spans) - self.max_spans]
            group = self._traces.get(span.trace_id)
            if group is None:
                group = self._traces[span.trace_id] = []
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            group.append(span)

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self.spans)

    def reset(self) -> None:
        with self._lock:
            self.spans.clear()
            self._traces.clear()

    def recent_traces(self, limit: int = 32) -> List[Dict[str, Any]]:
        """Newest `limit` traces from the flight recorder, each a
        {trace_id, spans: [span dicts]} group (the /debug/traces body)."""
        with self._lock:
            groups = list(self._traces.items())[-limit:]
        return [{"trace_id": tid,
                 "spans": [s.to_dict() for s in spans]}
                for tid, spans in reversed(groups)]

    def debug_traces_json(self, limit: int = 32) -> str:
        return json.dumps({"traces": self.recent_traces(limit)}, indent=1)


def chrome_trace_doc(events: List[Dict[str, Any]],
                     process_names: Dict[int, str],
                     thread_names: Dict[tuple, str]) -> Dict[str, Any]:
    """Assemble a Chrome trace-event document from data events plus
    lane names: ``M`` (metadata) records declare every pid as
    ``process_name`` and every (pid, tid) as ``thread_name``, so
    Perfetto groups lanes by component (scheduler child, binder
    worker, device worker) instead of showing bare numeric TIDs.
    Shared by the span export below and timeline.to_chrome_trace."""
    meta: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": name}}
        for pid, name in process_names.items()]
    meta += [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
         "args": {"name": name}}
        for (pid, tid), name in thread_names.items()]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def to_chrome_trace(spans: List[Span],
                    pid_attr: str = "process") -> Dict[str, Any]:
    """Render spans as Chrome trace-event JSON (Perfetto-loadable).

    Complete ("X") events on microsecond wall timestamps; each process
    (span attribute `pid_attr`, default span.attributes["process"]) gets
    its own pid lane and each recording THREAD its own named tid lane
    (scheduler loop, binder worker, server threads), so parent-child
    spans nest on the thread that ran them and worker-side spans land
    in a second process lane on the same wall clock.  Span events
    become instant ("i") events on the same track."""
    events: List[Dict[str, Any]] = []
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    for s in spans:
        proc = str(s.attributes.get(pid_attr, "scheduler"))
        pid = pids.setdefault(proc, len(pids) + 1)
        thread = getattr(s, "thread", "MainThread")
        tid = tids.setdefault((pid, thread), len(tids) + 1)
        ts_us = s.start_wall * 1e6
        events.append({
            "name": s.name, "ph": "X", "cat": "batch",
            "ts": ts_us, "dur": max(s.duration, 0.0) * 1e6,
            "pid": pid, "tid": tid,
            "args": {"trace_id": s.trace_id, "span_id": s.span_id,
                     "parent_span_id": s.parent_span_id,
                     **{k: v for k, v in s.attributes.items()
                        if k != pid_attr}},
        })
        for t, name, attrs in s.events:
            events.append({
                "name": name, "ph": "i", "cat": "batch", "s": "t",
                "ts": ts_us + (t - s.start_time) * 1e6,
                "pid": pid, "tid": tid,
                "args": dict(attrs),
            })
    return chrome_trace_doc(
        events,
        {pid: name for name, pid in pids.items()},
        {(pid, tid): thr for (pid, thr), tid in tids.items()})


# -- current-span propagation ----------------------------------------------
# The batch pipeline hands the root span from the scheduling loop to the
# batch backend (and its resolve closure) through a thread-local instead
# of widening every dispatch() signature across the backend ladder
# (ops/failover.py wraps backends; ops/remote.py subclasses them).

_current = threading.local()


def current_span() -> Optional[Span]:
    return getattr(_current, "span", None)


class use_span:
    """Context manager installing `span` as the thread's current span
    (restores the previous one on exit; None is allowed and clears it)."""

    def __init__(self, span: Optional[Span]):
        self.span = span
        self._prev: Optional[Span] = None

    def __enter__(self) -> Optional[Span]:
        self._prev = getattr(_current, "span", None)
        _current.span = self.span
        return self.span

    def __exit__(self, *exc) -> None:
        _current.span = self._prev


default_tracer_provider = TracerProvider()
