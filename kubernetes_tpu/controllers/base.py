"""Controller skeleton: informer events -> rate-limited workqueue ->
N sync workers -> idempotent sync(key).

Reference: the canonical controller pattern (SURVEY.md §3.4):
pkg/controller/replicaset/replica_set.go:528,533 (worker/processNextWorkItem)
— informer handlers enqueue keys, workers pop, sync, forget on success /
rate-limited requeue on error.
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import Client
from ..client.informer import SharedInformerFactory
from ..client.workqueue import RateLimitingQueue

logger = logging.getLogger(__name__)


class Controller:
    name = "controller"
    workers = 2
    max_requeues = 15

    def __init__(self, client: Client, factory: SharedInformerFactory):
        self.client = client
        self.factory = factory
        self.queue = RateLimitingQueue()
        self._threads: list[threading.Thread] = []
        self._stopped = threading.Event()

    # subclasses wire informers in __init__ and implement sync()
    def sync(self, key: str) -> None:
        raise NotImplementedError

    def enqueue(self, obj: Obj) -> None:
        self.queue.add(meta.namespaced_name(obj))

    def enqueue_key(self, key: str) -> None:
        self.queue.add(key)

    def run(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(target=self._worker,
                                 name=f"{self.name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stopped.set()
        self.queue.shut_down()

    def _worker(self) -> None:
        while True:
            key, shutdown = self.queue.get()
            if shutdown:
                return
            try:
                self.sync(key)
            except Exception:  # noqa: BLE001 - controller must survive
                if self.queue.rate_limiter.num_requeues(key) < self.max_requeues:
                    logger.exception("%s: sync(%s) failed; requeueing",
                                     self.name, key)
                    self.queue.add_rate_limited(key)
                else:
                    logger.exception("%s: sync(%s) failed too often; dropping",
                                     self.name, key)
                    self.queue.forget(key)
            else:
                self.queue.forget(key)
            finally:
                self.queue.done(key)


class Expectations:
    """Controller expectations (pkg/controller/controller_utils.go
    ControllerExpectations): dampen informer lag.  After a sync creates or
    deletes N children, it records N expected add/delete events; until the
    informer has delivered them (or the expectation times out), further
    syncs of that key must not mutate children — otherwise a second sync
    racing the informer re-creates/re-deletes the same diff."""

    TIMEOUT = 300.0  # ExpectationsTimeout (controller_utils.go:328)

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # key -> [pending_adds, pending_dels, set_time]
        self._by_key: dict[str, list] = {}

    def expect_creations(self, key: str, n: int) -> None:
        with self._lock:
            self._by_key[key] = [n, 0, time.monotonic()]

    def expect_deletions(self, key: str, n: int) -> None:
        with self._lock:
            self._by_key[key] = [0, n, time.monotonic()]

    def creation_observed(self, key: str) -> None:
        self._observed(key, 0)

    def deletion_observed(self, key: str) -> None:
        self._observed(key, 1)

    def _observed(self, key: str, idx: int) -> None:
        with self._lock:
            e = self._by_key.get(key)
            if e is not None:
                e[idx] -= 1

    def satisfied(self, key: str) -> bool:
        with self._lock:
            e = self._by_key.get(key)
            if e is None:
                return True
            if e[0] <= 0 and e[1] <= 0:
                del self._by_key[key]
                return True
            if time.monotonic() - e[2] > self.TIMEOUT:
                del self._by_key[key]
                return True
            return False

    def delete(self, key: str) -> None:
        with self._lock:
            self._by_key.pop(key, None)


def split_key(key: str) -> tuple[str, str]:
    ns, _, name = key.partition("/")
    return (ns, name) if name else ("", ns)


def owner_ref(obj: Obj, kind: str) -> Obj:
    return {"apiVersion": "v1", "kind": kind, "name": meta.name(obj),
            "uid": meta.uid(obj), "controller": True,
            "blockOwnerDeletion": True}


def is_owned_by(obj: Obj, owner: Obj) -> bool:
    ref = meta.controller_ref(obj)
    return ref is not None and ref.get("uid") == meta.uid(owner)
