"""Bootstrap token controllers.

Reference: pkg/controller/bootstrap/
  tokencleaner.go  - delete bootstrap token Secrets past their
                     `expiration` field
  bootstrapsigner.go - maintain the `cluster-info` ConfigMap in
                     kube-public, JWS-signed with each valid token (we
                     publish the kubeconfig stub + HMAC signatures).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import CONFIGMAPS, SECRETS
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)

BOOTSTRAP_TOKEN_TYPE = "bootstrap.kubernetes.io/token"
TOKEN_SECRET_NS = "kube-system"
CLUSTER_INFO_NS = "kube-public"


def build_cluster_info_kubeconfig(server_url: str = "",
                                  ca_pem: str = "") -> str:
    """The kubeconfig stub published in cluster-info.  JSON (a valid
    kubeconfig encoding) so join can parse it without a YAML dependency;
    carries the apiserver endpoint and CA bundle — the two facts the JWS
    exists to protect."""
    import json as _json
    cluster: dict = {}
    if server_url:
        cluster["server"] = server_url
    if ca_pem:
        cluster["certificate-authority-data"] = base64.b64encode(
            ca_pem.encode()).decode("ascii")
    return _json.dumps({
        "apiVersion": "v1", "kind": "Config",
        "clusters": [{"name": "", "cluster": cluster}],
    }, sort_keys=True)
CLUSTER_INFO_NAME = "cluster-info"


def _token_fields(secret: Obj) -> tuple[str, str] | None:
    data = secret.get("data") or {}
    tid, tsec = data.get("token-id"), data.get("token-secret")
    return (tid, tsec) if tid and tsec else None


class TokenCleaner(Controller):
    """Delete expired bootstrap tokens (tokencleaner.go)."""

    name = "tokencleaner"
    resync_seconds = 30.0

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.secret_informer = factory.informer(SECRETS)
        self.secret_informer.add_event_handler(self._on_secret)

    def _on_secret(self, type_, secret, old) -> None:
        if secret.get("type") == BOOTSTRAP_TOKEN_TYPE:
            self.enqueue(secret)

    def run(self) -> None:
        super().run()
        t = threading.Thread(target=self._tick, name="tokencleaner-tick",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _tick(self) -> None:
        while not self._stopped.wait(self.resync_seconds):
            for s in self.secret_informer.list(TOKEN_SECRET_NS):
                if s.get("type") == BOOTSTRAP_TOKEN_TYPE:
                    self.enqueue(s)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        secret = self.secret_informer.get(ns, name)
        if secret is None or secret.get("type") != BOOTSTRAP_TOKEN_TYPE:
            return
        exp = (secret.get("data") or {}).get("expiration")
        if exp is None:
            return
        try:
            expires = float(exp)
        except (TypeError, ValueError):
            logger.warning("bootstrap token %s: bad expiration %r", key, exp)
            return
        if time.time() >= expires:
            try:
                self.client.delete(SECRETS, ns, name)
            except kv.NotFoundError:
                pass


class BootstrapSigner(Controller):
    """Publish + sign the kube-public/cluster-info ConfigMap
    (bootstrapsigner.go): one `jws-kubeconfig-<token-id>` entry per live
    token, HMAC(token-secret, kubeconfig)."""

    name = "bootstrapsigner"

    def __init__(self, client, factory, kubeconfig: str = "",
                 server_url: str = "", ca_pem: str = ""):
        super().__init__(client, factory)
        # The signed payload must BIND cluster identity — endpoint + CA —
        # or the signature only proves token knowledge (bootstrapsigner.go
        # signs a kubeconfig carrying the CA bundle and server address).
        # When constructed from the manager registry (no explicit URL),
        # derive the endpoint from the HTTP client so the published
        # cluster-info stays joinable; in-process LocalClients have no
        # endpoint and publish a stub join must reject.
        if not server_url and hasattr(client, "host"):
            server_url = f"http://{client.host}:{client.port}"
        if not ca_pem:
            try:
                from .certificates import ClusterCA
                ca_pem = ClusterCA.shared().ca_pem()
            except Exception:  # cryptography unavailable: stub CA omitted
                ca_pem = ""
        self.kubeconfig = kubeconfig or build_cluster_info_kubeconfig(
            server_url, ca_pem)
        self.secret_informer = factory.informer(SECRETS)
        self.cm_informer = factory.informer(CONFIGMAPS)
        self.secret_informer.add_event_handler(self._on_change)
        self.cm_informer.add_event_handler(self._on_cm)

    def _on_change(self, type_, secret, old) -> None:
        if secret.get("type") == BOOTSTRAP_TOKEN_TYPE:
            self.enqueue_key(f"{CLUSTER_INFO_NS}/{CLUSTER_INFO_NAME}")

    def _on_cm(self, type_, cm, old) -> None:
        if (meta.namespace(cm) == CLUSTER_INFO_NS
                and meta.name(cm) == CLUSTER_INFO_NAME):
            self.enqueue(cm)

    def sync(self, key: str) -> None:
        sigs = {}
        now = time.time()
        for s in self.secret_informer.list(TOKEN_SECRET_NS):
            if s.get("type") != BOOTSTRAP_TOKEN_TYPE:
                continue
            exp = (s.get("data") or {}).get("expiration")
            if exp is not None:
                try:
                    if now >= float(exp):
                        continue
                except (TypeError, ValueError):
                    logger.warning("bootstrap token %s: bad expiration %r",
                                   meta.name(s), exp)
                    continue
            fields = _token_fields(s)
            if fields is None:
                continue
            tid, tsec = fields
            mac = hmac.new(tsec.encode(), self.kubeconfig.encode(),
                           hashlib.sha256).digest()
            sigs[f"jws-kubeconfig-{tid}"] = base64.urlsafe_b64encode(
                mac).decode("ascii")

        managed = {"kubeconfig": self.kubeconfig, **sigs}

        def merge(data: dict) -> dict:
            # only the kubeconfig + jws-* entries are ours; foreign keys
            # are preserved (bootstrapsigner.go updates signatures in place)
            out = {k: v for k, v in data.items()
                   if not k.startswith("jws-kubeconfig-")}
            out.update(managed)
            return out

        cm = self.cm_informer.get(CLUSTER_INFO_NS, CLUSTER_INFO_NAME)
        if cm is None:
            obj = meta.new_object("ConfigMap", CLUSTER_INFO_NAME,
                                  CLUSTER_INFO_NS)
            obj["data"] = dict(managed)
            try:
                self.client.create(CONFIGMAPS, obj)
            except kv.AlreadyExistsError:
                pass
        elif merge(cm.get("data") or {}) != (cm.get("data") or {}):
            def patch(o):
                o["data"] = merge(o.get("data") or {})
                return o
            try:
                self.client.guaranteed_update(CONFIGMAPS, CLUSTER_INFO_NS,
                                              CLUSTER_INFO_NAME, patch)
            except kv.NotFoundError:
                pass
