"""Certificates controllers: CSR approver + signer.

Reference: pkg/controller/certificates/
  approver/sarapprove.go  - auto-approve kubelet client CSRs whose usages/
                            signerName match the known profiles
  signer/signer.go        - sign Approved CSRs with the cluster CA, honoring
                            spec.expirationSeconds (capped), writing
                            status.certificate
  cleaner/cleaner.go      - GC CSRs: expired certs, long-Denied, long-Pending

The CA is generated in-process (cryptography lib): self-signed root, RSA
2048.  The reference loads --cluster-signing-cert-file; our ClusterCA is
that file's stand-in and is shared with the root-ca publisher.
"""

from __future__ import annotations

import base64
import datetime
import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import CSRS
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)

KUBELET_CLIENT_SIGNER = "kubernetes.io/kube-apiserver-client-kubelet"
KUBELET_SERVING_SIGNER = "kubernetes.io/kubelet-serving"
MAX_EXPIRATION_SECONDS = 365 * 24 * 3600
DEFAULT_EXPIRATION_SECONDS = 24 * 3600

_PENDING_TTL = 24 * 3600      # cleaner.go pendingExpiration (we use 24h)
_DENIED_TTL = 3600            # cleaner.go deniedExpiration simplification


class ClusterCA:
    """In-process cluster CA (the --cluster-signing-cert-file stand-in)."""

    _singleton = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID

        self.key = rsa.generate_private_key(public_exponent=65537,
                                            key_size=2048)
        name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                             "kubernetes-tpu-ca")])
        now = datetime.datetime.now(datetime.timezone.utc)
        self.cert = (x509.CertificateBuilder()
                     .subject_name(name).issuer_name(name)
                     .public_key(self.key.public_key())
                     .serial_number(x509.random_serial_number())
                     .not_valid_before(now)
                     .not_valid_after(now + datetime.timedelta(days=3650))
                     .add_extension(x509.BasicConstraints(ca=True,
                                                          path_length=None),
                                    critical=True)
                     .sign(self.key, hashes.SHA256()))

    @classmethod
    def shared(cls) -> "ClusterCA":
        with cls._lock:
            if cls._singleton is None:
                cls._singleton = cls()
            return cls._singleton

    def ca_pem(self) -> str:
        from cryptography.hazmat.primitives import serialization
        return self.cert.public_bytes(
            serialization.Encoding.PEM).decode("ascii")

    def sign_csr_pem(self, csr_pem: bytes, seconds: int) -> bytes:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization

        req = x509.load_pem_x509_csr(csr_pem)
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (x509.CertificateBuilder()
                   .subject_name(req.subject)
                   .issuer_name(self.cert.subject)
                   .public_key(req.public_key())
                   .serial_number(x509.random_serial_number())
                   .not_valid_before(now)
                   .not_valid_after(now + datetime.timedelta(seconds=seconds)))
        for ext in req.extensions:
            builder = builder.add_extension(ext.value, ext.critical)
        cert = builder.sign(self.key, hashes.SHA256())
        return cert.public_bytes(serialization.Encoding.PEM)


def csr_condition(csr: Obj, type_: str) -> Obj | None:
    for c in (csr.get("status") or {}).get("conditions") or ():
        if c.get("type") == type_:
            return c
    return None


def is_approved(csr: Obj) -> bool:
    return csr_condition(csr, "Approved") is not None


def is_denied(csr: Obj) -> bool:
    return csr_condition(csr, "Denied") is not None


class CSRApprovingController(Controller):
    """Auto-approve well-known kubelet CSR profiles (approver/sarapprove.go)."""

    name = "csrapproving"

    RECOGNIZED = {
        KUBELET_CLIENT_SIGNER: {"key encipherment", "digital signature",
                                "client auth"},
        KUBELET_SERVING_SIGNER: {"key encipherment", "digital signature",
                                 "server auth"},
    }

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.csr_informer = factory.informer(CSRS)
        self.csr_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        csr = self.csr_informer.get(ns, name)
        if csr is None or is_approved(csr) or is_denied(csr):
            return
        spec = csr.get("spec") or {}
        allowed = self.RECOGNIZED.get(spec.get("signerName"))
        if allowed is None:
            return  # not ours to approve
        usages = set(spec.get("usages") or ())
        if not usages or not usages.issubset(allowed):
            return

        def patch(o):
            conds = o.setdefault("status", {}).setdefault("conditions", [])
            if any(c.get("type") in ("Approved", "Denied") for c in conds):
                return o
            conds.append({"type": "Approved", "status": "True",
                          "reason": "AutoApproved",
                          "message": "auto-approved kubelet CSR",
                          "lastUpdateTime": time.time()})
            return o
        try:
            self.client.guaranteed_update(CSRS, ns, name, patch)
        except kv.NotFoundError:
            pass


class CSRSigningController(Controller):
    """Sign Approved CSRs with the cluster CA (signer/signer.go)."""

    name = "csrsigning"

    def __init__(self, client, factory, ca: ClusterCA | None = None):
        super().__init__(client, factory)
        self.ca = ca or ClusterCA.shared()
        self.csr_informer = factory.informer(CSRS)
        self.csr_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        csr = self.csr_informer.get(ns, name)
        if csr is None or not is_approved(csr) or is_denied(csr):
            return
        if (csr.get("status") or {}).get("certificate"):
            return  # already signed
        spec = csr.get("spec") or {}
        if spec.get("signerName") not in (KUBELET_CLIENT_SIGNER,
                                          KUBELET_SERVING_SIGNER):
            return
        req_pem = base64.b64decode(spec.get("request") or b"")
        seconds = min(int(spec.get("expirationSeconds")
                          or DEFAULT_EXPIRATION_SECONDS),
                      MAX_EXPIRATION_SECONDS)
        try:
            cert_pem = self.ca.sign_csr_pem(req_pem, seconds)
        except Exception as e:  # malformed request: record failure condition
            logger.warning("csr %s: cannot sign: %s", key, e)

            def fail(o):
                conds = o.setdefault("status", {}).setdefault("conditions", [])
                if not any(c.get("type") == "Failed" for c in conds):
                    conds.append({"type": "Failed", "status": "True",
                                  "reason": "SigningError", "message": str(e)})
                return o
            try:
                self.client.guaranteed_update(CSRS, ns, name, fail)
            except kv.NotFoundError:
                pass
            return

        def patch(o):
            st = o.setdefault("status", {})
            if not st.get("certificate"):
                st["certificate"] = base64.b64encode(cert_pem).decode("ascii")
            return o
        try:
            self.client.guaranteed_update(CSRS, ns, name, patch)
        except kv.NotFoundError:
            pass


class CSRCleanerController(Controller):
    """GC stale CSRs (cleaner/cleaner.go): denied >1h, pending >24h."""

    name = "csrcleaner"
    resync_seconds = 60.0

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.csr_informer = factory.informer(CSRS)

    def run(self) -> None:
        super().run()
        t = threading.Thread(target=self._tick, name="csrcleaner-tick",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _tick(self) -> None:
        while not self._stopped.wait(self.resync_seconds):
            for csr in self.csr_informer.list(None):
                self.enqueue(csr)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        csr = self.csr_informer.get(ns, name)
        if csr is None:
            return
        age = time.time() - (meta.creation_timestamp(csr) or time.time())
        expired = (is_denied(csr) and age > _DENIED_TTL) or (
            not is_approved(csr) and not is_denied(csr) and age > _PENDING_TTL)
        if expired:
            try:
                self.client.delete(CSRS, ns, name)
            except kv.NotFoundError:
                pass
