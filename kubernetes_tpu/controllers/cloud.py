"""Cloud controller manager: the cloud-provider-facing controllers.

Reference: cmd/cloud-controller-manager + staging/src/k8s.io/cloud-provider
— out-of-tree controllers driving a CloudProvider interface:
  service controller  (cloud-provider/controllers/service) - provision a
      cloud load balancer for Service type=LoadBalancer, publish its
      ingress IP in status.loadBalancer; deprovision on type change/delete
  route controller    (cloud-provider/controllers/route) - program cloud
      routes so each node's podCIDR is reachable; reconciled against the
      node list
  node controller     (cloud-provider/controllers/node) - decorate nodes
      with cloud metadata (provider id, zone/region labels) and clear the
      uninitialized taint

FakeCloudProvider is the in-process cloud (the reference ships exactly
this shape in cloud-provider/fake for its tests); a real provider
implements the same three surfaces.
"""

from __future__ import annotations

import logging
import threading

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import NODES, SERVICES
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)

UNINITIALIZED_TAINT = "node.cloudprovider.kubernetes.io/uninitialized"


class FakeCloudProvider:
    """cloud-provider/fake shape: LBs from an IP pool, route table dict,
    static zone metadata."""

    def __init__(self, zone: str = "tpu-zone-a", region: str = "tpu-region"):
        self.zone, self.region = zone, region
        self._lock = threading.Lock()
        self._lbs: dict[str, str] = {}      # service key -> external ip
        self._next_ip = 1
        self.routes: dict[str, str] = {}    # node name -> podCIDR

    # LoadBalancer surface (cloudprovider.LoadBalancer)
    def ensure_load_balancer(self, svc_key: str) -> str:
        with self._lock:
            ip = self._lbs.get(svc_key)
            if ip is None:
                ip = f"203.0.113.{self._next_ip}"
                self._next_ip += 1
                self._lbs[svc_key] = ip
            return ip

    def ensure_load_balancer_deleted(self, svc_key: str) -> None:
        with self._lock:
            self._lbs.pop(svc_key, None)

    # Routes surface (cloudprovider.Routes)
    def create_route(self, node: str, cidr: str) -> None:
        with self._lock:
            self.routes[node] = cidr

    def delete_route(self, node: str) -> None:
        with self._lock:
            self.routes.pop(node, None)

    # InstancesV2 surface
    def instance_metadata(self, node: str) -> dict:
        return {"providerID": f"fake://{self.region}/{self.zone}/{node}",
                "zone": self.zone, "region": self.region}


class CloudServiceController(Controller):
    """Service type=LoadBalancer <-> cloud LB (service_controller.go)."""

    name = "cloud-service"

    def __init__(self, client, factory, cloud: FakeCloudProvider | None = None):
        super().__init__(client, factory)
        self.cloud = cloud or FakeCloudProvider()
        self.svc_informer = factory.informer(SERVICES)
        self.svc_informer.add_event_handler(self._on_svc)

    def _on_svc(self, type_, svc, old) -> None:
        self.enqueue(svc)
        if type_ == kv.DELETED:
            self.cloud.ensure_load_balancer_deleted(meta.namespaced_name(svc))

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        svc = self.svc_informer.get(ns, name)
        if svc is None:
            self.cloud.ensure_load_balancer_deleted(key)
            return
        if (svc.get("spec") or {}).get("type") != "LoadBalancer":
            # type changed away: deprovision + clear published ingress
            self.cloud.ensure_load_balancer_deleted(key)
            if ((svc.get("status") or {}).get("loadBalancer") or {}).get(
                    "ingress"):
                def clear(o):
                    (o.get("status") or {}).pop("loadBalancer", None)
                    return o
                try:
                    self.client.guaranteed_update(SERVICES, ns, name, clear)
                except kv.NotFoundError:
                    pass
            return
        ip = self.cloud.ensure_load_balancer(key)
        ingress = [{"ip": ip}]
        if ((svc.get("status") or {}).get("loadBalancer") or {}).get(
                "ingress") == ingress:
            return

        def publish(o):
            o.setdefault("status", {})["loadBalancer"] = {"ingress": ingress}
            return o
        try:
            self.client.guaranteed_update(SERVICES, ns, name, publish)
        except kv.NotFoundError:
            pass


class CloudRouteController(Controller):
    """node podCIDR -> cloud route table (route_controller.go)."""

    name = "cloud-route"

    def __init__(self, client, factory, cloud: FakeCloudProvider | None = None):
        super().__init__(client, factory)
        self.cloud = cloud or FakeCloudProvider()
        self.node_informer = factory.informer(NODES)
        self.node_informer.add_event_handler(self._on_node)

    def _on_node(self, type_, node, old) -> None:
        if type_ == kv.DELETED:
            self.cloud.delete_route(meta.name(node))
        else:
            self.enqueue(node)

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        node = self.node_informer.get("", name)
        if node is None:
            self.cloud.delete_route(name)
            return
        cidr = (node.get("spec") or {}).get("podCIDR")
        if cidr:
            self.cloud.create_route(name, cidr)
            # NetworkUnavailable=False once the route exists
            conds = (node.get("status") or {}).get("conditions") or []
            if not any(c.get("type") == "NetworkUnavailable"
                       and c.get("status") == "False" for c in conds):
                def patch(o):
                    cs = o.setdefault("status", {}).setdefault(
                        "conditions", [])
                    cs[:] = [c for c in cs
                             if c.get("type") != "NetworkUnavailable"]
                    cs.append({"type": "NetworkUnavailable",
                               "status": "False",
                               "reason": "RouteCreated"})
                    return o
                try:
                    self.client.guaranteed_update(NODES, "", name, patch)
                except kv.NotFoundError:
                    pass


class CloudNodeController(Controller):
    """Cloud metadata onto nodes + uninitialized-taint removal
    (node_controller.go)."""

    name = "cloud-node"

    def __init__(self, client, factory, cloud: FakeCloudProvider | None = None):
        super().__init__(client, factory)
        self.cloud = cloud or FakeCloudProvider()
        self.node_informer = factory.informer(NODES)
        self.node_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        node = self.node_informer.get("", name)
        if node is None:
            return
        md = self.cloud.instance_metadata(name)
        labels = meta.labels(node)
        want_labels = {"topology.kubernetes.io/zone": md["zone"],
                       "topology.kubernetes.io/region": md["region"]}
        has_taint = any(
            t.get("key") == UNINITIALIZED_TAINT
            for t in (node.get("spec") or {}).get("taints") or ())
        done = ((node.get("spec") or {}).get("providerID") == md["providerID"]
                and all(labels.get(k) == v for k, v in want_labels.items())
                and not has_taint)
        if done:
            return

        def patch(o):
            o.setdefault("spec", {})["providerID"] = md["providerID"]
            o["metadata"].setdefault("labels", {}).update(want_labels)
            taints = (o.get("spec") or {}).get("taints") or []
            o["spec"]["taints"] = [t for t in taints
                                   if t.get("key") != UNINITIALIZED_TAINT]
            return o
        try:
            self.client.guaranteed_update(NODES, "", name, patch)
        except kv.NotFoundError:
            pass


class CloudControllerManager:
    """cmd/cloud-controller-manager: the three controllers over one cloud."""

    def __init__(self, client, factory, cloud: FakeCloudProvider | None = None):
        self.cloud = cloud or FakeCloudProvider()
        self.controllers = [
            CloudServiceController(client, factory, self.cloud),
            CloudRouteController(client, factory, self.cloud),
            CloudNodeController(client, factory, self.cloud),
        ]

    def run(self) -> None:
        for c in self.controllers:
            c.run()

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()
