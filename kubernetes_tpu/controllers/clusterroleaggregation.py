"""ClusterRole aggregation controller.

Reference: pkg/controller/clusterroleaggregation/clusterroleaggregation_
controller.go — a ClusterRole carrying `aggregationRule.
clusterRoleSelectors` gets its `rules` REPLACED by the union of the
rules of every ClusterRole matching any of the selectors (this is how
`admin`/`edit`/`view` pick up aggregated CRD permissions).  Any
ClusterRole event re-queues every aggregating role, since a label
change anywhere can change some union.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.labels import selector_from_dict
from ..api.meta import Obj
from ..client.clientset import CLUSTERROLES
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)


class ClusterRoleAggregationController(Controller):
    name = "clusterrole-aggregation"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.role_informer = factory.informer(CLUSTERROLES)
        self.role_informer.add_event_handler(self._on_role)

    def _on_role(self, type_, role: Obj, old: Obj | None) -> None:
        # any role's labels/rules feeding any union may have changed:
        # requeue every aggregating role (the reference does the same —
        # clusterroleaggregation_controller.go enqueues all)
        for r in self.role_informer.list(None):
            if (r.get("aggregationRule") or {}).get("clusterRoleSelectors"):
                self.enqueue_key(meta.name(r))

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        role = self.role_informer.get("", name)
        if role is None:
            return
        selectors = (role.get("aggregationRule")
                     or {}).get("clusterRoleSelectors") or []
        if not selectors:
            return
        compiled = [selector_from_dict(s) for s in selectors]
        union: list = []
        seen: set = set()
        for r in sorted(self.role_informer.list(None), key=meta.name):
            if meta.name(r) == name:
                continue  # never aggregate a role into itself
            labels = meta.labels(r)
            if not any(c.matches(labels) for c in compiled):
                continue
            for rule in r.get("rules") or ():
                fp = repr(sorted(rule.items()))
                if fp not in seen:
                    seen.add(fp)
                    union.append(rule)
        if (role.get("rules") or []) == union:
            return

        def patch(cur: Obj) -> Obj:
            cur["rules"] = union
            return cur
        try:
            self.client.guaranteed_update(CLUSTERROLES, "", name, patch)
        except kv.NotFoundError:
            pass
