"""CronJob controller.

Reference: pkg/controller/cronjob/ — cron-schedule parsing (robfig/cron
vendored upstream; a standard 5-field parser here), per-tick Job creation
named <cronjob>-<scheduled-unix-minute>, concurrencyPolicy
Allow/Forbid/Replace, suspend, and successful/failed jobs history limits.
Time-driven: a ticker thread reconciles every `tick` seconds;
reconcile_once(now) is the testable core.
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import CRONJOBS, JOBS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv
from .base import is_owned_by, owner_ref

logger = logging.getLogger(__name__)


class CronParseError(ValueError):
    pass


def _parse_field(field: str, lo: int, hi: int) -> set[int]:
    out: set[int] = set()
    for part in field.split(","):
        try:
            step = 1
            if "/" in part:
                part, step_s = part.split("/", 1)
                step = int(step_s)
            if step <= 0:
                raise CronParseError("step must be positive: %r" % field)
            if part == "*" or part == "":
                rng = range(lo, hi + 1)
            elif "-" in part:
                a, b = part.split("-", 1)
                rng = range(int(a), int(b) + 1)
            else:
                rng = range(int(part), int(part) + 1)
        except ValueError:
            raise CronParseError("invalid cron field %r" % field)
        for v in rng:
            if v < lo or v > hi:
                raise CronParseError("value %d out of range [%d,%d]"
                                     % (v, lo, hi))
            # steps anchor at the range start (vixie cron: 1-23/2 = odd)
            if (v - rng.start) % step == 0:
                out.add(v)
    return out


class CronSchedule:
    """Standard 5-field cron: minute hour day-of-month month day-of-week."""

    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) != 5:
            raise CronParseError("cron expression needs 5 fields: %r" % expr)
        self.minutes = _parse_field(fields[0], 0, 59)
        self.hours = _parse_field(fields[1], 0, 23)
        self.dom = _parse_field(fields[2], 1, 31)
        self.months = _parse_field(fields[3], 1, 12)
        self.dow = _parse_field(fields[4], 0, 6)  # 0 = Sunday
        self._dom_star = fields[2] == "*"
        self._dow_star = fields[4] == "*"
        # reject never-matching dom/month combos ('0 0 31 2 *') at parse
        # time: otherwise next_after scans its whole horizon every tick.
        # Only the dom-governed case (dow='*') can be infeasible — with a
        # restricted dow, vixie OR semantics still fires on dow matches.
        if not self._dom_star and self._dow_star:
            max_day = {1: 31, 2: 29, 3: 31, 4: 30, 5: 31, 6: 30, 7: 31,
                       8: 31, 9: 30, 10: 31, 11: 30, 12: 31}
            if all(min(self.dom) > max_day[m] for m in self.months):
                raise CronParseError(
                    "schedule never matches: day-of-month %s in months %s"
                    % (sorted(self.dom), sorted(self.months)))

    def matches(self, t: time.struct_time) -> bool:
        if t.tm_min not in self.minutes or t.tm_hour not in self.hours \
                or t.tm_mon not in self.months:
            return False
        dom_ok = t.tm_mday in self.dom
        dow_ok = ((t.tm_wday + 1) % 7) in self.dow  # struct_time: Mon=0
        if self._dom_star and self._dow_star:
            return True
        if self._dom_star:
            return dow_ok
        if self._dow_star:
            return dom_ok
        return dom_ok or dow_ok  # vixie cron OR semantics

    def next_after(self, ts: float, horizon_days: int = 366) -> float | None:
        """Next matching minute strictly after ts."""
        t = int(ts // 60 + 1) * 60
        for _ in range(horizon_days * 24 * 60):
            if self.matches(time.localtime(t)):
                return float(t)
            t += 60
        return None


class CronJobController:
    name = "cronjob"

    def __init__(self, client: Client, factory: SharedInformerFactory,
                 tick: float = 10.0):
        self.client = client
        self.cj_informer = factory.informer(CRONJOBS)
        self.job_informer = factory.informer(JOBS)
        self.tick = tick
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick):
            try:
                self.reconcile_once(time.time())
            except Exception:  # noqa: BLE001
                logger.exception("cronjob reconcile failed")

    # -- core (syncCronJob) ----------------------------------------------

    def reconcile_once(self, now: float) -> None:
        for cj in self.cj_informer.list(None):
            try:
                self._sync_one(cj, now)
            except Exception as e:  # noqa: BLE001 — one bad CronJob must
                logger.error("cronjob %s: %s", meta.namespaced_name(cj), e)

    def _sync_one(self, cj: Obj, now: float) -> None:
        spec = cj.get("spec") or {}
        if spec.get("suspend"):
            return
        sched = CronSchedule(spec.get("schedule", ""))
        ns, name = meta.namespace(cj), meta.name(cj)
        status = cj.get("status") or {}
        last = status.get("lastScheduleTime", 0.0)
        created = meta.creation_timestamp(cj) or 0.0
        # the most recent scheduled minute <= now after `last`, never
        # before the CronJob existed (upstream getRecentUnmetScheduleTimes)
        due = None
        t = sched.next_after(max(last, created, now - 24 * 3600))
        while t is not None and t <= now:
            due = t
            t = sched.next_after(t)
        if due is None:
            return
        active = [j for j in self.job_informer.list(ns)
                  if is_owned_by(j, cj) and not self._job_finished(j)]
        policy = spec.get("concurrencyPolicy", "Allow")
        if active and policy == "Forbid":
            return
        if active and policy == "Replace":
            for j in active:
                try:
                    self.client.delete(JOBS, ns, meta.name(j))
                except kv.NotFoundError:
                    pass
        self._create_job(cj, due)
        self._record_schedule(ns, name, due)
        self._gc_history(cj, ns, spec)

    @staticmethod
    def _job_finished(job: Obj) -> bool:
        conds = (job.get("status") or {}).get("conditions") or []
        return any(c.get("type") in ("Complete", "Failed")
                   and c.get("status") == "True" for c in conds)

    def _create_job(self, cj: Obj, due: float) -> None:
        ns = meta.namespace(cj)
        job_name = f"{meta.name(cj)}-{int(due // 60)}"
        tmpl = ((cj.get("spec") or {}).get("jobTemplate") or {})
        job = meta.new_object("Job", job_name, ns)
        job["metadata"]["ownerReferences"] = [owner_ref(cj, "CronJob")]
        job["metadata"]["annotations"] = {
            "cronjob.kubernetes.io/scheduled-at": str(due)}
        job["spec"] = meta.deep_copy(tmpl.get("spec") or {})
        try:
            self.client.create(JOBS, job)
        except kv.AlreadyExistsError:
            pass  # already created for this tick (idempotent name)

    def _record_schedule(self, ns: str, name: str, due: float) -> None:
        def patch(o):
            o.setdefault("status", {})["lastScheduleTime"] = due
            return o
        try:
            self.client.guaranteed_update(CRONJOBS, ns, name, patch)
        except kv.NotFoundError:
            pass

    def _gc_history(self, cj: Obj, ns: str, spec: dict) -> None:
        keep_ok = spec.get("successfulJobsHistoryLimit", 3)
        keep_bad = spec.get("failedJobsHistoryLimit", 1)
        finished = [j for j in self.job_informer.list(ns)
                    if is_owned_by(j, cj) and self._job_finished(j)]
        by_time = sorted(finished, key=lambda j: float(
            (j["metadata"].get("annotations") or {})
            .get("cronjob.kubernetes.io/scheduled-at", 0)))
        ok = [j for j in by_time if any(
            c.get("type") == "Complete" and c.get("status") == "True"
            for c in (j.get("status") or {}).get("conditions", []))]
        bad = [j for j in by_time if j not in ok]
        for j in ok[:-keep_ok] if keep_ok else ok:
            self._delete_job(ns, meta.name(j))
        for j in bad[:-keep_bad] if keep_bad else bad:
            self._delete_job(ns, meta.name(j))

    def _delete_job(self, ns: str, name: str) -> None:
        try:
            self.client.delete(JOBS, ns, name)
        except kv.NotFoundError:
            pass
