"""DaemonSet controller.

Reference: pkg/controller/daemon/ — one pod per eligible node.  Node
eligibility: nodeSelector match + required node affinity + taints
tolerated (daemon pods get the standard not-ready/unreachable NoExecute
and NoSchedule tolerations).  Modern upstream routes daemon pods through
the scheduler with a node-affinity pin; we do the same: the pod carries a
requiredDuringScheduling nodeAffinity for its target node and the default
scheduler binds it (so resource fit is still enforced on TPU path too).
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import DAEMONSETS, NODES, PODS
from ..store import kv
from .base import Controller, Expectations, is_owned_by, owner_ref, split_key
from .replicaset import pod_is_active, pod_is_ready

logger = logging.getLogger(__name__)

DAEMON_TOLERATIONS = [
    {"key": "node.kubernetes.io/not-ready", "operator": "Exists",
     "effect": "NoExecute"},
    {"key": "node.kubernetes.io/unreachable", "operator": "Exists",
     "effect": "NoExecute"},
    {"key": "node.kubernetes.io/unschedulable", "operator": "Exists",
     "effect": "NoSchedule"},
]


def _affinity_matches(pod_spec: dict, node: Obj) -> bool:
    """Template requiredDuringScheduling node affinity (matchExpressions
    over labels; matchFields over metadata.name), OR across terms."""
    terms = (((pod_spec.get("affinity") or {}).get("nodeAffinity") or {})
             .get("requiredDuringSchedulingIgnoredDuringExecution") or {}) \
        .get("nodeSelectorTerms")
    if not terms:
        return True
    node_labels = meta.labels(node)
    for term in terms:
        ok = True
        for req in term.get("matchExpressions", []):
            val = node_labels.get(req.get("key"))
            op = req.get("operator", "In")
            if op == "In":
                ok = val in (req.get("values") or [])
            elif op == "NotIn":
                ok = val not in (req.get("values") or [])
            elif op == "Exists":
                ok = req.get("key") in node_labels
            elif op == "DoesNotExist":
                ok = req.get("key") not in node_labels
            if not ok:
                break
        for req in term.get("matchFields", []) if ok else ():
            if req.get("key") == "metadata.name":
                ok = meta.name(node) in (req.get("values") or [])
            if not ok:
                break
        if ok:
            return True
    return False


def _node_matches(ds: Obj, node: Obj) -> bool:
    pod_spec = (((ds.get("spec") or {}).get("template") or {})
                .get("spec") or {})
    sel = pod_spec.get("nodeSelector") or {}
    node_labels = meta.labels(node)
    if not all(node_labels.get(k) == v for k, v in sel.items()):
        return False
    if not _affinity_matches(pod_spec, node):
        return False
    # untolerated NoSchedule/NoExecute taints exclude the node
    tolerations = (((ds.get("spec") or {}).get("template") or {})
                   .get("spec", {}).get("tolerations") or []) + DAEMON_TOLERATIONS
    for taint in (node.get("spec") or {}).get("taints", []):
        if taint.get("effect") not in ("NoSchedule", "NoExecute"):
            continue
        if not any(_tolerates(t, taint) for t in tolerations):
            return False
    return True


def _tolerates(tol: dict, taint: dict) -> bool:
    if tol.get("effect") and tol["effect"] != taint.get("effect"):
        return False
    if tol.get("operator", "Equal") == "Exists":
        return not tol.get("key") or tol["key"] == taint.get("key")
    return (tol.get("key") == taint.get("key")
            and tol.get("value", "") == taint.get("value", ""))


class DaemonSetController(Controller):
    name = "daemonset"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.ds_informer = factory.informer(DAEMONSETS)
        self.pod_informer = factory.informer(PODS)
        self.node_informer = factory.informer(NODES)
        self.expectations = Expectations()
        self.ds_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)
        self.node_informer.add_event_handler(self._on_node)

    def _on_pod(self, type_, pod: Obj, old) -> None:
        ref = meta.controller_ref(pod)
        if ref and ref.get("kind") == "DaemonSet":
            key = f"{meta.namespace(pod)}/{ref['name']}"
            if type_ == kv.ADDED:
                self.expectations.creation_observed(key)
            elif type_ == kv.DELETED:
                self.expectations.deletion_observed(key)
            self.enqueue_key(key)

    def _on_node(self, type_, node: Obj, old) -> None:
        # node churn re-syncs every daemonset
        for ds in self.ds_informer.list(None):
            self.enqueue(ds)

    def _pod_node(self, pod: Obj) -> str:
        """Target node: bound nodeName, or the affinity pin pre-binding."""
        bound = meta.pod_node_name(pod)
        if bound:
            return bound
        terms = ((((pod.get("spec") or {}).get("affinity") or {})
                  .get("nodeAffinity") or {})
                 .get("requiredDuringSchedulingIgnoredDuringExecution") or {})
        for term in terms.get("nodeSelectorTerms", []):
            for f in term.get("matchFields", []):
                if f.get("key") == "metadata.name" and f.get("values"):
                    return f["values"][0]
        return ""

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        ds = self.ds_informer.get(ns, name)
        if ds is None:
            self.expectations.delete(key)
            return
        nodes = {meta.name(n): n for n in self.node_informer.list(None)}
        eligible = {n for n, node in nodes.items() if _node_matches(ds, node)}
        by_node: dict[str, Obj] = {}
        for p in self.pod_informer.list(ns):
            if is_owned_by(p, ds) and pod_is_active(p):
                by_node.setdefault(self._pod_node(p), p)

        if self.expectations.satisfied(key):
            to_create = sorted(eligible - set(by_node))
            to_delete = sorted(set(by_node) - eligible)
            if to_create:
                self.expectations.expect_creations(key, len(to_create))
                for node_name in to_create:
                    try:
                        if not self._create_pod(ds, node_name):
                            self.expectations.creation_observed(key)
                    except Exception:
                        self.expectations.creation_observed(key)
                        raise
            if to_delete:
                self.expectations.expect_deletions(key, len(to_delete))
                for node_name in to_delete:
                    try:
                        self.client.delete(PODS, ns,
                                           meta.name(by_node[node_name]))
                    except kv.NotFoundError:
                        self.expectations.deletion_observed(key)

        scheduled = sum(1 for n in by_node if n in eligible)
        ready = sum(1 for n, p in by_node.items()
                    if n in eligible and pod_is_ready(p))
        status = {"desiredNumberScheduled": len(eligible),
                  "currentNumberScheduled": scheduled,
                  "numberReady": ready,
                  "numberMisscheduled": len(set(by_node) - eligible),
                  "observedGeneration": ds["metadata"].get("generation", 0)}
        if (ds.get("status") or {}) != status:
            def patch(o):
                o["status"] = status
                return o
            try:
                self.client.guaranteed_update(DAEMONSETS, ns, name, patch)
            except kv.NotFoundError:
                pass

    def _create_pod(self, ds: Obj, node_name: str) -> bool:
        ns, ds_name = meta.namespace(ds), meta.name(ds)
        tmpl = (ds.get("spec") or {}).get("template") or {}
        pod = meta.new_object("Pod", f"{ds_name}-{node_name}", ns)
        tmpl_meta = tmpl.get("metadata") or {}
        pod["metadata"]["labels"] = dict(tmpl_meta.get("labels") or {})
        pod["metadata"]["ownerReferences"] = [owner_ref(ds, "DaemonSet")]
        pod["spec"] = meta.deep_copy(tmpl.get("spec") or {"containers": [
            {"name": "c0", "image": "img"}]})
        # pin to the node via required node affinity; scheduler binds it
        pod["spec"].setdefault("affinity", {})["nodeAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchFields": [{
                    "key": "metadata.name", "operator": "In",
                    "values": [node_name]}]}]}}
        pod["spec"].setdefault("tolerations", []).extend(DAEMON_TOLERATIONS)
        pod["spec"].setdefault("schedulerName", "default-scheduler")
        try:
            self.client.create(PODS, pod)
            return True
        except kv.AlreadyExistsError:
            return False
