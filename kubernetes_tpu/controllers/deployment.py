"""Deployment controller.

Reference: pkg/controller/deployment/ — syncDeployment: find/create the
ReplicaSet for the current pod template (identified by a template hash
label), scale it to spec.replicas, scale old ReplicaSets down (rolling
update reduced to: surge the new RS fully, drain old RSes as new pods
become ready; Recreate = drain first), and mirror status.
"""

from __future__ import annotations

import hashlib
import json
import logging

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import DEPLOYMENTS, REPLICASETS
from ..store import kv
from .base import Controller, is_owned_by, owner_ref, split_key
from .replicaset import pod_is_ready

logger = logging.getLogger(__name__)

HASH_LABEL = "pod-template-hash"


def template_hash(template: Obj) -> str:
    canon = json.dumps(template, sort_keys=True)
    return hashlib.sha1(canon.encode()).hexdigest()[:10]


class DeploymentController(Controller):
    name = "deployment"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.dep_informer = factory.informer(DEPLOYMENTS)
        self.rs_informer = factory.informer(REPLICASETS)
        self.dep_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.rs_informer.add_event_handler(self._on_rs)

    def _on_rs(self, type_, rs: Obj, old) -> None:
        ref = meta.controller_ref(rs)
        if ref and ref.get("kind") == "Deployment":
            self.enqueue_key(f"{meta.namespace(rs)}/{ref['name']}")

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        dep = self.dep_informer.get(ns, name)
        if dep is None:
            return
        spec = dep.get("spec") or {}
        replicas = spec.get("replicas", 1)
        template = spec.get("template") or {}
        thash = template_hash(template)
        strategy = (spec.get("strategy") or {}).get("type", "RollingUpdate")

        owned = [rs for rs in self.rs_informer.list(ns) if is_owned_by(rs, dep)]
        new_rs = next((rs for rs in owned
                       if meta.labels(rs).get(HASH_LABEL) == thash), None)
        old_rses = [rs for rs in owned
                    if meta.labels(rs).get(HASH_LABEL) != thash]

        if new_rs is None:
            if strategy == "Recreate" and any(
                    (rs.get("status") or {}).get("replicas", 0) > 0
                    for rs in old_rses):
                self._scale_all(old_rses, 0)
                return  # next sync creates the new RS once old ones drain
            new_rs = self._create_rs(dep, template, thash, replicas)
            if new_rs is None:
                return

        if (new_rs.get("spec") or {}).get("replicas") != replicas:
            self._scale(new_rs, replicas)

        # rolling: drain old RSes as the new one becomes ready
        new_ready = (new_rs.get("status") or {}).get("readyReplicas", 0)
        for rs in old_rses:
            cur = (rs.get("spec") or {}).get("replicas", 0)
            if cur > 0:
                target = max(0, replicas - new_ready)
                if target < cur:
                    self._scale(rs, target)
        # GC fully-drained old RSes beyond revisionHistoryLimit (default 10)
        drained = [rs for rs in old_rses
                   if (rs.get("spec") or {}).get("replicas", 0) == 0
                   and (rs.get("status") or {}).get("replicas", 0) == 0]
        limit = spec.get("revisionHistoryLimit", 10)
        for rs in drained[:-limit] if limit else drained:
            try:
                self.client.delete(REPLICASETS, ns, meta.name(rs))
            except kv.NotFoundError:
                pass

        self._update_status(dep, new_rs, old_rses, replicas)

    def _create_rs(self, dep: Obj, template: Obj, thash: str,
                   replicas: int) -> Obj | None:
        ns = meta.namespace(dep)
        rs = meta.new_object("ReplicaSet", f"{meta.name(dep)}-{thash}", ns)
        labels = dict((template.get("metadata") or {}).get("labels") or {})
        labels[HASH_LABEL] = thash
        tmpl = meta.deep_copy(template)
        tmpl.setdefault("metadata", {}).setdefault("labels", {})[HASH_LABEL] = thash
        rs["metadata"]["labels"] = labels
        rs["metadata"]["ownerReferences"] = [owner_ref(dep, "Deployment")]
        rs["spec"] = {"replicas": replicas,
                      "selector": {"matchLabels": labels},
                      "template": tmpl}
        try:
            return self.client.create(REPLICASETS, rs)
        except kv.AlreadyExistsError:
            return self.rs_informer.get(ns, meta.name(rs))

    def _scale(self, rs: Obj, replicas: int) -> None:
        def patch(o):
            o.setdefault("spec", {})["replicas"] = replicas
            return o
        try:
            self.client.guaranteed_update(REPLICASETS, meta.namespace(rs),
                                          meta.name(rs), patch)
        except kv.NotFoundError:
            pass

    def _scale_all(self, rses: list[Obj], replicas: int) -> None:
        for rs in rses:
            if (rs.get("spec") or {}).get("replicas", 0) != replicas:
                self._scale(rs, replicas)

    def _update_status(self, dep: Obj, new_rs: Obj, old_rses: list[Obj],
                       want: int) -> None:
        total = ready = updated = 0
        for rs in [new_rs, *old_rses]:
            st = rs.get("status") or {}
            total += st.get("replicas", 0)
            ready += st.get("readyReplicas", 0)
        updated = (new_rs.get("status") or {}).get("replicas", 0)
        conds = []
        if ready >= want:
            conds.append({"type": "Available", "status": "True"})
        status = {"replicas": total, "readyReplicas": ready,
                  "updatedReplicas": updated, "availableReplicas": ready,
                  "conditions": conds,
                  "observedGeneration": dep["metadata"].get("generation", 0)}
        if (dep.get("status") or {}) == status:
            return

        def patch(o):
            o["status"] = status
            return o
        try:
            self.client.guaranteed_update(DEPLOYMENTS, meta.namespace(dep),
                                          meta.name(dep), patch)
        except kv.NotFoundError:
            pass
