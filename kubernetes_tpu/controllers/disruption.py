"""Disruption controller.

Reference: pkg/controller/disruption/ — maintains PodDisruptionBudget
status: expectedPods (from the owning controller's scale), currentHealthy,
desiredHealthy (from minAvailable/maxUnavailable IntOrString), and
disruptionsAllowed, which the apiserver's eviction subresource consumes.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import PDBS, PODS
from ..store import kv
from .base import Controller, split_key
from .replicaset import pod_is_ready

logger = logging.getLogger(__name__)


def _scaled(value, expected: int) -> int:
    if isinstance(value, str) and value.endswith("%"):
        return -(-int(float(value[:-1]) * expected) // 100)  # ceil
    return int(value)


class DisruptionController(Controller):
    name = "disruption"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.pdb_informer = factory.informer(PDBS)
        self.pod_informer = factory.informer(PODS)
        self.pdb_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, pod: Obj, old) -> None:
        labels = meta.labels(pod)
        for pdb in self.pdb_informer.list(meta.namespace(pod)):
            sel = ((pdb.get("spec") or {}).get("selector") or {}) \
                .get("matchLabels", {})
            if sel and all(labels.get(k) == v for k, v in sel.items()):
                self.enqueue(pdb)

    def _expected(self, matching: list[Obj], ns: str) -> int:
        """Sum the scale of every distinct owning controller (upstream
        getExpectedScale); unowned pods count themselves."""
        owners: dict[tuple, int] = {}
        unowned = 0
        for p in matching:
            ref = meta.controller_ref(p)
            if ref and ref.get("kind") in ("ReplicaSet", "StatefulSet",
                                           "ReplicationController"):
                key = (ref["kind"], ref["name"])
                if key in owners:
                    continue
                try:
                    owner = self.client.get(ref["kind"].lower() + "s", ns,
                                            ref["name"])
                    owners[key] = int((owner.get("spec") or {})
                                      .get("replicas", 1))
                except kv.NotFoundError:
                    owners[key] = 0
            else:
                unowned += 1
        if not owners:
            return len(matching)
        return sum(owners.values()) + unowned

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pdb = self.pdb_informer.get(ns, name)
        if pdb is None:
            return
        spec = pdb.get("spec") or {}
        sel = (spec.get("selector") or {}).get("matchLabels", {})
        matching = [p for p in self.pod_informer.list(ns)
                    if sel and all(meta.labels(p).get(k) == v
                                   for k, v in sel.items())]
        # upstream counts only Ready pods as healthy (disruption.go
        # countHealthyPods); the hollow kubelet sets the Ready condition
        healthy = sum(1 for p in matching
                      if pod_is_ready(p)
                      and meta.deletion_timestamp(p) is None)
        expected = self._expected(matching, ns)
        if "minAvailable" in spec:
            desired = _scaled(spec["minAvailable"], expected)
        elif "maxUnavailable" in spec:
            desired = expected - _scaled(spec["maxUnavailable"], expected)
        else:
            desired = 0
        allowed = max(0, healthy - desired)
        status = {"expectedPods": expected, "currentHealthy": healthy,
                  "desiredHealthy": desired, "disruptionsAllowed": allowed,
                  "observedGeneration": pdb["metadata"].get("generation", 0)}
        if (pdb.get("status") or {}) != status:
            def patch(o):
                o["status"] = status
                return o
            try:
                self.client.guaranteed_update(PDBS, ns, name, patch)
            except kv.NotFoundError:
                pass
