"""Endpoints controller: Services -> ready pod IPs.

Reference: pkg/controller/endpoint/ — for each Service, select ready pods
by spec.selector and write an Endpoints object with their podIPs + ports.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.labels import selector_from_match_labels
from ..api.meta import Obj
from ..client.clientset import ENDPOINTS, PODS, SERVICES
from ..store import kv
from .base import Controller, split_key
from .replicaset import pod_is_ready

logger = logging.getLogger(__name__)


class EndpointsController(Controller):
    name = "endpoints"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.svc_informer = factory.informer(SERVICES)
        self.pod_informer = factory.informer(PODS)
        self.svc_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, pod: Obj, old) -> None:
        ns = meta.namespace(pod)
        for svc in self.svc_informer.list(ns):
            sel = (svc.get("spec") or {}).get("selector") or {}
            if sel and selector_from_match_labels(sel).matches(meta.labels(pod)):
                self.enqueue(svc)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        svc = self.svc_informer.get(ns, name)
        if svc is None:
            try:
                self.client.delete(ENDPOINTS, ns, name)
            except kv.NotFoundError:
                pass
            return
        sel = (svc.get("spec") or {}).get("selector") or {}
        if not sel:
            return  # headless/external services manage their own endpoints
        selector = selector_from_match_labels(sel)
        addresses = []
        for pod in self.pod_informer.list(ns):
            if (selector.matches(meta.labels(pod)) and pod_is_ready(pod)
                    and (pod.get("status") or {}).get("podIP")):
                addresses.append({"ip": pod["status"]["podIP"],
                                  "nodeName": meta.pod_node_name(pod),
                                  "targetRef": {"kind": "Pod",
                                                "name": meta.name(pod),
                                                "uid": meta.uid(pod)}})
        ports = [{"name": p.get("name", ""), "port": p.get("targetPort",
                                                           p.get("port")),
                  "protocol": p.get("protocol", "TCP")}
                 for p in (svc.get("spec") or {}).get("ports") or ()]
        subsets = [{"addresses": addresses, "ports": ports}] if addresses else []
        ep = meta.new_object("Endpoints", name, ns)
        ep["subsets"] = subsets
        try:
            cur = self.client.get(ENDPOINTS, ns, name)
            if cur.get("subsets") != subsets:
                self.client.guaranteed_update(
                    ENDPOINTS, ns, name,
                    lambda o: {**o, "subsets": subsets})
        except kv.NotFoundError:
            self.client.create(ENDPOINTS, ep)
