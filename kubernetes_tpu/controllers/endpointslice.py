"""EndpointSlice controller.

Reference: pkg/controller/endpointslice/ (reconciler.go) — for every Service
with a selector, maintain EndpointSlice objects naming the ready pod
endpoints, chunked at maxEndpointsPerSlice (default 100).  Slices carry the
`kubernetes.io/service-name` label tying them to their Service; stale slices
are deleted, changed ones updated in place (the reference computes a minimal
create/update/delete plan per sync; we regenerate the desired slice set and
diff it against the informer's view).
"""

from __future__ import annotations

import hashlib
import logging

from ..api import meta
from ..api.labels import selector_from_dict
from ..api.meta import Obj
from ..client.clientset import ENDPOINTSLICES, PODS, SERVICES
from ..store import kv
from .base import Controller, owner_ref, split_key
from .replicaset import pod_is_ready

logger = logging.getLogger(__name__)

MAX_ENDPOINTS_PER_SLICE = 100
SERVICE_NAME_LABEL = "kubernetes.io/service-name"


def _numeric_or_service_port(pt: dict):
    tp = pt.get("targetPort", pt.get("port"))
    return tp if isinstance(tp, int) else pt.get("port")


def _resolve_ports(svc_ports: list, pod: Obj) -> list[dict]:
    """Per-endpoint port resolution: a string targetPort names a container
    port on the pod (reference resolves named ports per endpoint in
    endpointslice/reconciler.go); unresolvable names fall back to the
    service port so the proxier never sees a non-numeric backend port."""
    out = []
    containers = (pod.get("spec") or {}).get("containers") or []
    for pt in svc_ports:
        tp = pt.get("targetPort", pt.get("port"))
        if isinstance(tp, str):
            resolved = None
            for c in containers:
                for cp in c.get("ports") or []:
                    if cp.get("name") == tp:
                        resolved = cp.get("containerPort")
                        break
                if resolved is not None:
                    break
            tp = resolved if resolved is not None else pt.get("port")
        out.append({"name": pt.get("name", ""), "port": tp,
                    "protocol": pt.get("protocol", "TCP")})
    return out


class EndpointSliceController(Controller):
    name = "endpointslice"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.svc_informer = factory.informer(SERVICES)
        self.pod_informer = factory.informer(PODS)
        self.slice_informer = factory.informer(ENDPOINTSLICES)
        self.svc_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_: str, pod: Obj, old: Obj | None) -> None:
        ns = meta.namespace(pod)
        labels = meta.labels(pod)
        old_labels = meta.labels(old) if old else {}
        for svc in self.svc_informer.list(ns):
            sel = (svc.get("spec") or {}).get("selector")
            if not sel:
                continue
            s = selector_from_dict({"matchLabels": sel})
            if s.matches(labels) or (old is not None and s.matches(old_labels)):
                self.enqueue(svc)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        svc = self.svc_informer.get(ns, name)
        # slices owned by ANOTHER manager (the mirroring controller's
        # managed-by label) are never ours to reconcile or delete —
        # reference reconciler filters on managed-by the same way
        existing = [sl for sl in self.slice_informer.list(ns)
                    if meta.labels(sl).get(SERVICE_NAME_LABEL) == name
                    and meta.labels(sl).get(
                        "endpointslice.kubernetes.io/managed-by",
                        "endpointslice-controller.k8s.io")
                    == "endpointslice-controller.k8s.io"]
        if svc is None or not (svc.get("spec") or {}).get("selector"):
            for sl in existing:
                self._delete(ns, meta.name(sl))
            return
        sel = selector_from_dict(
            {"matchLabels": (svc["spec"] or {}).get("selector") or {}})
        svc_ports = list(svc["spec"].get("ports") or ())
        # endpoints grouped by their RESOLVED port numbers: a named
        # targetPort can map to different container ports on different
        # pods, and slice ports are per-slice, so each distinct mapping
        # gets its own slice group (reference reconciler behavior)
        groups: dict[tuple, list[dict]] = {}
        for p in self.pod_informer.list(ns):
            # unready pods are included with ready=False (slices publish
            # readiness as a condition, unlike legacy Endpoints subsets)
            if (sel.matches(meta.labels(p)) and meta.pod_node_name(p)
                    and meta.deletion_timestamp(p) is None
                    and not meta.pod_is_terminal(p)):
                ports = _resolve_ports(svc_ports, p)
                groups.setdefault(
                    tuple((pt["name"], pt["port"], pt["protocol"])
                          for pt in ports), []).append({
                              "addresses": [((p.get("status") or {})
                                             .get("podIP")) or "0.0.0.0",],
                              "conditions": {"ready": pod_is_ready(p)},
                              "nodeName": meta.pod_node_name(p),
                              "targetRef": {"kind": "Pod", "namespace": ns,
                                            "name": meta.name(p),
                                            "uid": meta.uid(p)},
                          })
        if not groups:
            groups[tuple((pt.get("name", ""),
                          _numeric_or_service_port(pt), pt.get(
                              "protocol", "TCP")) for pt in svc_ports)] = []

        desired: list[Obj] = []
        for ports_key in sorted(groups):
            endpoints = sorted(groups[ports_key],
                               key=lambda e: e["targetRef"]["name"])
            ports = [{"name": nm_, "port": port_, "protocol": proto_}
                     for nm_, port_, proto_ in ports_key]
            # slice names are stable per port-group (digest suffix), so a
            # group appearing/vanishing never renames other groups' slices
            # (a shared running index would delete+recreate them and spam
            # every proxier with no-op watch events)
            gid = hashlib.sha256(repr(ports_key).encode()).hexdigest()[:8]
            chunks = [endpoints[i:i + MAX_ENDPOINTS_PER_SLICE]
                      for i in range(0, len(endpoints),
                                     MAX_ENDPOINTS_PER_SLICE)] or [[]]
            for i, chunk in enumerate(chunks):
                sl = meta.new_object("EndpointSlice",
                                     f"{name}-{gid}-{i}", ns)
                sl["metadata"]["labels"] = {SERVICE_NAME_LABEL: name}
                sl["metadata"]["ownerReferences"] = [owner_ref(svc,
                                                               "Service")]
                sl["addressType"] = "IPv4"
                sl["endpoints"] = chunk
                sl["ports"] = ports
                desired.append(sl)

        want = {meta.name(sl): sl for sl in desired}
        have = {meta.name(sl): sl for sl in existing}
        for nm, sl in want.items():
            cur = have.get(nm)
            if cur is None:
                try:
                    self.client.create(ENDPOINTSLICES, sl)
                except kv.AlreadyExistsError:
                    self.enqueue_key(key)
            elif (cur.get("endpoints") != sl["endpoints"]
                  or cur.get("ports") != sl["ports"]):
                def patch(o, _sl=sl):
                    o["endpoints"] = _sl["endpoints"]
                    o["ports"] = _sl["ports"]
                    return o
                try:
                    self.client.guaranteed_update(ENDPOINTSLICES, ns, nm, patch)
                except kv.NotFoundError:
                    self.enqueue_key(key)
        for nm in have:
            if nm not in want:
                self._delete(ns, nm)

    def _delete(self, ns: str, nm: str) -> None:
        try:
            self.client.delete(ENDPOINTSLICES, ns, nm)
        except kv.NotFoundError:
            pass
