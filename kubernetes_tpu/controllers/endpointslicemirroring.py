"""EndpointSlice mirroring controller.

Reference: pkg/controller/endpointslicemirroring/ — custom Endpoints
objects (their Service has NO selector, so the normal EndpointSlice
controller ignores the Service) are mirrored into EndpointSlices so
slice-only consumers (the proxier here reads slices) see
manually-managed backends too.  Skipped when the Endpoints carries the
`endpointslice.kubernetes.io/skip-mirror` label or the Service does
not exist; mirrored slices carry the service-name label plus
`endpointslice.kubernetes.io/managed-by: endpointslicemirroring-
controller.k8s.io` and are deleted when their Endpoints goes away.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import ENDPOINTS, ENDPOINTSLICES, SERVICES
from ..store import kv
from .base import Controller, owner_ref, split_key
from .endpointslice import SERVICE_NAME_LABEL

logger = logging.getLogger(__name__)

SKIP_MIRROR_LABEL = "endpointslice.kubernetes.io/skip-mirror"
MANAGED_BY_LABEL = "endpointslice.kubernetes.io/managed-by"
MANAGED_BY = "endpointslicemirroring-controller.k8s.io"


class EndpointSliceMirroringController(Controller):
    name = "endpointslicemirroring"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.ep_informer = factory.informer(ENDPOINTS)
        self.svc_informer = factory.informer(SERVICES)
        self.ep_informer.add_event_handler(
            lambda t, ep, old: self.enqueue(ep))
        self.svc_informer.add_event_handler(
            lambda t, svc, old: self.enqueue(svc))
        # recover mirrors that something else deleted/modified
        factory.informer(ENDPOINTSLICES).add_event_handler(
            self._on_slice)

    def _on_slice(self, type_, sl: Obj, old: Obj | None) -> None:
        labels = meta.labels(sl)
        if labels.get(MANAGED_BY_LABEL) == MANAGED_BY \
                and labels.get(SERVICE_NAME_LABEL):
            self.enqueue_key(f"{meta.namespace(sl)}/"
                             f"{labels[SERVICE_NAME_LABEL]}")

    def _mirror_slices(self, ep: Obj) -> list[Obj]:
        """Desired slices for one Endpoints object: one slice per
        subset (custom Endpoints are small; the reference also chunks
        at 1000/slice)."""
        ns, name = meta.namespace(ep), meta.name(ep)
        out = []
        for i, subset in enumerate(ep.get("subsets") or ()):
            endpoints = [
                {"addresses": [a.get("ip")],
                 "conditions": {"ready": True},
                 **({"targetRef": a["targetRef"]}
                    if a.get("targetRef") else {})}
                for a in subset.get("addresses") or ()]
            endpoints += [
                {"addresses": [a.get("ip")],
                 "conditions": {"ready": False}}
                for a in subset.get("notReadyAddresses") or ()]
            if not endpoints:
                continue
            out.append({
                "apiVersion": "discovery.k8s.io/v1",
                "kind": "EndpointSlice",
                "metadata": {
                    "name": f"{name}-mirror-{i}",
                    "namespace": ns,
                    "labels": {SERVICE_NAME_LABEL: name,
                               MANAGED_BY_LABEL: MANAGED_BY},
                    "ownerReferences": [owner_ref(ep, "Endpoints")],
                },
                "addressType": "IPv4",
                "endpoints": endpoints,
                "ports": [
                    {"name": p.get("name", ""), "port": p.get("port"),
                     "protocol": p.get("protocol", "TCP")}
                    for p in subset.get("ports") or ()],
            })
        return out

    def _existing_mirrors(self, ns: str, name: str) -> list[Obj]:
        return [s for s in self.factory.informer(ENDPOINTSLICES).list(ns)
                if (meta.labels(s).get(MANAGED_BY_LABEL) == MANAGED_BY
                    and meta.labels(s).get(SERVICE_NAME_LABEL) == name)]

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        ep = self.ep_informer.get(ns, name)
        svc = self.svc_informer.get(ns, name)
        mirror = (
            ep is not None and not meta.deletion_timestamp(ep)
            and SKIP_MIRROR_LABEL not in meta.labels(ep)
            and svc is not None
            and not (svc.get("spec") or {}).get("selector"))
        desired = self._mirror_slices(ep) if mirror else []
        want = {meta.name(s): s for s in desired}
        have = {meta.name(s): s for s in self._existing_mirrors(ns, name)}
        for stale in set(have) - set(want):
            try:
                self.client.delete(ENDPOINTSLICES, ns, stale)
            except kv.NotFoundError:
                pass
        for nm, slice_ in want.items():
            cur = have.get(nm)
            if cur is None:
                try:
                    self.client.create(ENDPOINTSLICES, slice_)
                except kv.AlreadyExistsError:
                    pass
            elif (cur.get("endpoints") != slice_["endpoints"]
                  or cur.get("ports") != slice_["ports"]):
                def patch(c, slice_=slice_):
                    c["endpoints"] = slice_["endpoints"]
                    c["ports"] = slice_["ports"]
                    return c
                try:
                    self.client.guaranteed_update(ENDPOINTSLICES, ns, nm,
                                                  patch)
                except kv.NotFoundError:
                    pass
