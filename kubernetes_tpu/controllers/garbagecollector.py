"""Garbage collector: cascading deletion via ownerReferences.

Reference: pkg/controller/garbagecollector/ — the dependency graph builder
watches everything; when an owner disappears its dependents are deleted
(background cascading).  Reduced: we track the (kind -> resource) pairs the
framework serves, index dependents by owner uid, and delete orphans whose
controller owner no longer exists.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import (
    DEPLOYMENTS, JOBS, PODS, PVCS, REPLICASETS, REPLICATIONCONTROLLERS,
)
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)

KIND_TO_RESOURCE = {"ReplicaSet": REPLICASETS, "Deployment": DEPLOYMENTS,
                    "Job": JOBS, "Pod": PODS,
                    "ReplicationController": REPLICATIONCONTROLLERS}
WATCHED = [PODS, REPLICASETS, JOBS, PVCS]


class GarbageCollector(Controller):
    name = "garbagecollector"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self._informers = {}
        for res in WATCHED:
            inf = factory.informer(res)
            self._informers[res] = inf
            inf.add_event_handler(
                lambda t, obj, old, res=res: self.enqueue_key(
                    f"{res}|{meta.namespaced_name(obj)}"))
        # owner kinds we must watch for deletions to re-check dependents
        # (PODS is already in WATCHED; it owns ephemeral-volume PVCs)
        for res in (REPLICASETS, DEPLOYMENTS, JOBS, REPLICATIONCONTROLLERS,
                    PODS):
            factory.informer(res).add_event_handler(self._on_owner_event)

    def _on_owner_event(self, type_: str, obj: Obj, old) -> None:
        if type_ != kv.DELETED:
            return
        # owner gone: enqueue all dependents
        uid = meta.uid(obj)
        for res, inf in self._informers.items():
            for dep in inf.list():
                ref = meta.controller_ref(dep)
                if ref and ref.get("uid") == uid:
                    self.enqueue_key(f"{res}|{meta.namespaced_name(dep)}")

    def sync(self, key: str) -> None:
        res, _, nsname = key.partition("|")
        ns, name = split_key(nsname)
        inf = self._informers.get(res)
        obj = inf.get(ns, name) if inf else None
        if obj is None:
            return
        ref = meta.controller_ref(obj)
        if ref is None:
            return
        owner_res = KIND_TO_RESOURCE.get(ref.get("kind"))
        if owner_res is None:
            return
        owner_ns = ns if owner_res != "nodes" else ""
        try:
            owner = self.client.get(owner_res, owner_ns, ref["name"])
            if meta.uid(owner) != ref.get("uid"):
                raise kv.NotFoundError("uid mismatch (owner recreated)")
        except kv.NotFoundError:
            logger.info("gc: deleting orphan %s/%s (owner %s/%s gone)",
                        res, nsname, ref.get("kind"), ref.get("name"))
            try:
                self.client.delete(res, ns, name)
            except kv.NotFoundError:
                pass
