"""Garbage collector: ownerReference dependency graph + cascading deletion.

Reference: pkg/controller/garbagecollector/ —
  graph_builder.go: a GraphBuilder watches every resource and maintains
    an owner->dependents uid graph (including "virtual" nodes for owners
    it has only seen referenced, never observed);
  garbagecollector.go attemptToDeleteItem: classify an item's owners as
    solid (exists), dangling (gone), or waitingForDependentsDeletion
    (terminating in foreground); any solid owner keeps the item, all
    dangling deletes it, waiting owners + blockOwnerDeletion push the
    delete down in foreground;
  foregroundDeletion finalizer: a Foreground delete parks the owner
    terminating until no blocking dependents remain, then the GC strips
    the finalizer and the storage layer completes the delete;
  orphan finalizer: an Orphan delete strips ownerReferences from all
    dependents first, so they survive the owner.

Deviation from the reference: discovery-driven "watch the world" becomes
a fixed list of the resources this control plane serves (we have one
API surface, not arbitrary CRD sets — CRD-backed resources can be added
to WATCHED at construction).
"""

from __future__ import annotations

import logging
import threading

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import (
    CONFIGMAPS, CRONJOBS, DAEMONSETS, DEPLOYMENTS, ENDPOINTSLICES, JOBS,
    PODGROUPS, PODS, PVCS, REPLICASETS, REPLICATIONCONTROLLERS, SECRETS,
    SERVICES, STATEFULSETS,
)
from ..store import kv
from .base import Controller

logger = logging.getLogger(__name__)

FOREGROUND_FINALIZER = meta.FOREGROUND_FINALIZER
ORPHAN_FINALIZER = meta.ORPHAN_FINALIZER

KIND_TO_RESOURCE = {
    "Pod": PODS, "ReplicaSet": REPLICASETS, "Deployment": DEPLOYMENTS,
    "Job": JOBS, "CronJob": CRONJOBS, "StatefulSet": STATEFULSETS,
    "DaemonSet": DAEMONSETS,
    "ReplicationController": REPLICATIONCONTROLLERS,
    "Service": SERVICES, "ConfigMap": CONFIGMAPS, "Secret": SECRETS,
    "PersistentVolumeClaim": PVCS, "PodGroup": PODGROUPS,
    "EndpointSlice": ENDPOINTSLICES,
}

WATCHED = (PODS, REPLICASETS, DEPLOYMENTS, JOBS, CRONJOBS, STATEFULSETS,
           DAEMONSETS, REPLICATIONCONTROLLERS, SERVICES, CONFIGMAPS,
           SECRETS, PVCS, PODGROUPS, ENDPOINTSLICES)


class _Node:
    """One object in the dependency graph (graph_builder.go node)."""

    __slots__ = ("uid", "resource", "ns", "name", "owner_refs",
                 "dependents", "virtual", "terminating_foreground")

    def __init__(self, uid, resource="", ns="", name="", virtual=False):
        self.uid = uid
        self.resource = resource
        self.ns = ns
        self.name = name
        self.owner_refs: list[dict] = []
        self.dependents: set[str] = set()  # uids
        self.virtual = virtual
        self.terminating_foreground = False


def owner_references(obj: Obj) -> list[dict]:
    return (obj.get("metadata") or {}).get("ownerReferences") or []


class GarbageCollector(Controller):
    """Graph builder + deletion workers in one controller."""

    name = "garbagecollector"
    workers = 2

    def __init__(self, client, factory, watched=WATCHED):
        super().__init__(client, factory)
        self._glock = threading.Lock()
        self._graph: dict[str, _Node] = {}  # uid -> node
        self._informers = {}
        for res in watched:
            inf = factory.informer(res)
            self._informers[res] = inf
            inf.add_event_handler(
                lambda t, obj, old, res=res: self._on_event(t, obj, res))

    # -- graph maintenance (graph_builder.go processGraphChanges) --------

    def _on_event(self, type_: str, obj: Obj, res: str) -> None:
        uid = meta.uid(obj)
        if not uid:
            return
        md = obj.get("metadata") or {}
        if type_ == kv.DELETED:
            with self._glock:
                node = self._graph.pop(uid, None)
                if node:
                    for ref in node.owner_refs:
                        owner = self._graph.get(ref.get("uid", ""))
                        if owner:
                            owner.dependents.discard(uid)
                dependents = list(node.dependents) if node else []
                owner_uids = [r.get("uid", "") for r in
                              (node.owner_refs if node else [])]
            # dependents may now be orphans; owners waiting in foreground
            # may now be unblocked
            for dep_uid in dependents:
                self._enqueue_uid("delete", dep_uid)
            for ouid in owner_uids:
                self._enqueue_uid("delete", ouid)
            return

        refs = owner_references(obj)
        terminating = bool(md.get("deletionTimestamp"))
        fins = md.get("finalizers") or []
        with self._glock:
            node = self._graph.get(uid)
            if node is None:
                node = self._graph[uid] = _Node(uid)
            elif node.virtual:
                node.virtual = False  # observed for real now
            node.resource, node.ns, node.name = \
                res, md.get("namespace", ""), md.get("name", "")
            # re-point owner edges
            for ref in node.owner_refs:
                o = self._graph.get(ref.get("uid", ""))
                if o:
                    o.dependents.discard(uid)
            node.owner_refs = refs
            for ref in refs:
                ouid = ref.get("uid", "")
                if not ouid:
                    continue
                owner = self._graph.get(ouid)
                if owner is None:
                    # virtual node: referenced but never observed — it
                    # may exist outside our watch set or not at all
                    owner = self._graph[ouid] = _Node(
                        ouid,
                        KIND_TO_RESOURCE.get(ref.get("kind", ""), ""),
                        md.get("namespace", ""), ref.get("name", ""),
                        virtual=True)
                owner.dependents.add(uid)
            node.terminating_foreground = (
                terminating and FOREGROUND_FINALIZER in fins)

        if refs:
            self._enqueue_uid("delete", uid)
        if terminating and FOREGROUND_FINALIZER in fins:
            # push the foreground delete down, and check whether it can
            # already complete
            with self._glock:
                deps = list(self._graph.get(uid, _Node(uid)).dependents)
            for dep_uid in deps:
                self._enqueue_uid("delete", dep_uid)
            self._enqueue_uid("delete", uid)
        if terminating and ORPHAN_FINALIZER in fins:
            self._enqueue_uid("orphan", uid)

    def _enqueue_uid(self, action: str, uid: str) -> None:
        if uid:
            self.enqueue_key(f"{action}|{uid}")

    # -- workers ---------------------------------------------------------

    def sync(self, key: str) -> None:
        action, _, uid = key.partition("|")
        with self._glock:
            node = self._graph.get(uid)
            snapshot = None
            if node is not None:
                snapshot = (node.resource, node.ns, node.name,
                            list(node.owner_refs), node.virtual,
                            node.terminating_foreground,
                            list(node.dependents))
        if snapshot is None:
            return
        res, ns, name, _, virtual, _, dependents = snapshot
        if virtual or not res:
            return
        # decide from the LIVE object, not the graph snapshot — informer
        # lag would otherwise delete freshly-detached dependents
        # (the reference's attemptToDeleteItem also re-reads, gc.go:507)
        try:
            live = self.client.get(res, ns, name)
        except kv.NotFoundError:
            return
        if meta.uid(live) != uid:
            return  # same name, different object
        md = live.get("metadata") or {}
        refs = owner_references(live)
        term_fg = bool(md.get("deletionTimestamp")) and \
            FOREGROUND_FINALIZER in (md.get("finalizers") or [])
        if action == "orphan":
            self._attempt_to_orphan(res, ns, name, uid, dependents)
        else:
            self._attempt_to_delete(res, ns, name, uid, refs, term_fg,
                                    dependents)

    # attemptToDeleteItem (garbagecollector.go:497)
    def _attempt_to_delete(self, res, ns, name, uid, refs, term_fg,
                           dependents) -> None:
        if term_fg:
            self._maybe_finish_foreground(res, ns, name, uid, dependents)
            # fall through: a foreground-terminating item can itself be a
            # dependent of something else, but its own deletion is already
            # in progress — nothing more to do for its owners
            return
        if not refs:
            return
        solid, dangling, waiting = [], [], []
        for ref in refs:
            owner_res = KIND_TO_RESOURCE.get(ref.get("kind", ""))
            if owner_res is None:
                solid.append(ref)  # unknown kind: never cascade (be safe)
                continue
            owner_ns = "" if owner_res in ("nodes",) else ns
            try:
                owner = self.client.get(owner_res, owner_ns,
                                        ref.get("name", ""))
            except kv.NotFoundError:
                dangling.append(ref)
                continue
            if meta.uid(owner) != ref.get("uid"):
                dangling.append(ref)  # owner was recreated: not my owner
                continue
            omd = owner.get("metadata") or {}
            if omd.get("deletionTimestamp") and FOREGROUND_FINALIZER in (
                    omd.get("finalizers") or []):
                waiting.append(ref)
            else:
                solid.append(ref)
        if solid:
            return
        if waiting:
            blocking = [r for r in waiting if r.get("blockOwnerDeletion")]
            # owner is foreground-terminating: propagate the delete down,
            # in foreground if this item blocks and has dependents itself
            policy = "Foreground" if (blocking and dependents) else None
            self._delete(res, ns, name, uid, policy)
            return
        if dangling:
            logger.info("gc: deleting %s/%s %s (all owners gone)",
                        res, ns, name)
            self._delete(res, ns, name, uid,
                         "Foreground" if dependents else None)

    def _delete(self, res, ns, name, uid, policy) -> None:
        try:
            cur = self.client.get(res, ns, name)
            if meta.uid(cur) != uid:
                return  # recreated under the same name: leave it alone
            self.client.delete(res, ns, name, propagation_policy=policy)
        except kv.NotFoundError:
            pass

    # the foregroundDeletion finalizer strip
    # (garbagecollector.go processDeletingDependentsItem)
    def _maybe_finish_foreground(self, res, ns, name, uid,
                                 dependents) -> None:
        blocking = []
        with self._glock:
            for dep_uid in dependents:
                dep = self._graph.get(dep_uid)
                if dep is None:
                    continue
                for ref in dep.owner_refs:
                    if ref.get("uid") == uid and ref.get(
                            "blockOwnerDeletion"):
                        blocking.append(dep_uid)
        if blocking:
            return  # still waiting on dependents
        def strip(cur):
            fins = (cur["metadata"].get("finalizers") or [])
            cur["metadata"]["finalizers"] = [
                f for f in fins if f != FOREGROUND_FINALIZER]
            return cur
        try:
            self.client.guaranteed_update(res, ns, name, strip)
        except kv.NotFoundError:
            pass

    # attemptToOrphan: detach dependents, then release the owner
    def _attempt_to_orphan(self, res, ns, name, uid, dependents) -> None:
        with self._glock:
            dep_info = [(d.resource, d.ns, d.name)
                        for d in (self._graph.get(u) for u in dependents)
                        if d is not None and not d.virtual]
        for dres, dns, dname in dep_info:
            def detach(cur):
                cur["metadata"]["ownerReferences"] = [
                    r for r in owner_references(cur)
                    if r.get("uid") != uid]
                if not cur["metadata"]["ownerReferences"]:
                    del cur["metadata"]["ownerReferences"]
                return cur
            try:
                self.client.guaranteed_update(dres, dns, dname, detach)
            except kv.NotFoundError:
                pass
        def strip(cur):
            fins = (cur["metadata"].get("finalizers") or [])
            cur["metadata"]["finalizers"] = [
                f for f in fins if f != ORPHAN_FINALIZER]
            return cur
        try:
            self.client.guaranteed_update(res, ns, name, strip)
        except kv.NotFoundError:
            pass

    # -- introspection (debugger / tests) --------------------------------

    def graph_size(self) -> int:
        with self._glock:
            return len(self._graph)

    def dependents_of(self, uid: str) -> set[str]:
        with self._glock:
            node = self._graph.get(uid)
            return set(node.dependents) if node else set()
