"""Horizontal Pod Autoscaler controller (autoscaling/v2 semantics).

Reference: pkg/controller/podautoscaler/horizontal.go —
  computeReplicasForMetrics: desired per metric spec =
  ceil(current * currentMetricValue / targetMetricValue); the FINAL
  recommendation is the MAX across metrics;
  tolerance (default 0.1): a ratio within [0.9, 1.1] does not scale;
  stabilization (stabilizeRecommendationWithBehaviors): scale-down acts
  on the max recommendation over its window (default 300s), scale-up on
  the min over its window (default 0 — instant);
  behavior policies (normalizeDesiredReplicasWithBehaviors): scaleUp /
  scaleDown each carry [{type: Pods|Percent, value, periodSeconds}]
  limits computed against the scale-event history, combined by
  selectPolicy Max|Min|Disabled.

There is no metrics-server in this stack; pod usage comes from a
pluggable metrics getter.  The default reads pod annotations — the same
seam upstream fills with the resource-metrics / custom-metrics APIs:
  metrics.kubernetes.io/cpu-usage        milliCPU (Resource cpu)
  metrics.kubernetes.io/memory-usage     bytes    (Resource memory)
  metrics.kubernetes.io/custom/<name>    float    (Pods custom metric)

The autoscaling/v1 shape (spec.targetCPUUtilizationPercentage) is
accepted and treated as a single Resource-cpu Utilization metric, the
same conversion the reference applies.
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta, quantity
from ..api.meta import Obj
from ..client.clientset import HPAS, PODS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv

logger = logging.getLogger(__name__)

USAGE_ANNOTATION = "metrics.kubernetes.io/cpu-usage"
MEMORY_ANNOTATION = "metrics.kubernetes.io/memory-usage"
CUSTOM_PREFIX = "metrics.kubernetes.io/custom/"

TOLERANCE = 0.1  # horizontal.go defaultTestingTolerance / --horizontal-pod-autoscaler-tolerance

SCALE_TARGETS = {"Deployment": "deployments", "ReplicaSet": "replicasets",
                 "StatefulSet": "statefulsets"}


def default_metrics_getter(pod: Obj, metric: str = "cpu") -> float | None:
    """-> metric sample for one pod, or None.

    metric: "cpu" (milliCPU), "memory" (bytes), or a custom metric name.
    """
    ann = pod["metadata"].get("annotations") or {}
    try:
        if metric == "cpu":
            raw = ann.get(USAGE_ANNOTATION)
            return None if raw is None else float(
                quantity.parse_cpu_milli(raw))
        if metric == "memory":
            raw = ann.get(MEMORY_ANNOTATION)
            return None if raw is None else float(
                quantity.parse_mem_bytes(raw))
        raw = ann.get(CUSTOM_PREFIX + metric)
        return None if raw is None else float(quantity.parse_quantity(raw))
    except (ValueError, TypeError):
        return None


class HorizontalPodAutoscaler:
    name = "horizontalpodautoscaler"

    def __init__(self, client: Client, factory: SharedInformerFactory,
                 tick: float = 15.0, metrics_getter=default_metrics_getter,
                 downscale_stabilization: float = 300.0):
        self.client = client
        self.hpa_informer = factory.informer(HPAS)
        self.pod_informer = factory.informer(PODS)
        self.tick = tick
        self.metrics_getter = metrics_getter
        self.downscale_stabilization = downscale_stabilization
        self._recommendations: dict[str, list[tuple[float, int]]] = {}
        # scale-event history per HPA: [(time, replica_delta)] — behavior
        # policy rate limits are computed against it (horizontal.go
        # scaleUpEvents/scaleDownEvents)
        self._scale_events: dict[str, list[tuple[float, int]]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _get_metric(self, pod: Obj, metric: str) -> float | None:
        try:
            return self.metrics_getter(pod, metric)
        except TypeError:
            # 1-arg getter (pre-v2 seam): serves cpu only
            return self.metrics_getter(pod) if metric == "cpu" else None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick):
            try:
                self.reconcile_once(time.time())
            except Exception:  # noqa: BLE001
                logger.exception("hpa reconcile failed")

    def reconcile_once(self, now: float) -> None:
        live = set()
        for hpa in self.hpa_informer.list(None):
            live.add(meta.namespaced_name(hpa))
            try:
                self._sync_one(hpa, now)
            except Exception as e:  # noqa: BLE001 — one bad HPA must not
                logger.warning("hpa %s: %s", meta.namespaced_name(hpa), e)
        # drop stabilization windows of deleted HPAs
        for key in list(self._recommendations):
            if key not in live:
                del self._recommendations[key]

    @staticmethod
    def _metric_specs(spec: dict) -> list[dict]:
        """spec.metrics (v2), or the v1 targetCPUUtilizationPercentage
        converted to a Resource-cpu Utilization metric."""
        if spec.get("metrics"):
            return spec["metrics"]
        pct = spec.get("targetCPUUtilizationPercentage", 80)
        return [{"type": "Resource",
                 "resource": {"name": "cpu",
                              "target": {"type": "Utilization",
                                         "averageUtilization": pct}}}]

    def _pod_request(self, pod: Obj, resource_name: str) -> float:
        parse = (quantity.parse_cpu_milli if resource_name == "cpu"
                 else quantity.parse_mem_bytes)
        return float(sum(parse(
            ((c.get("resources") or {}).get("requests") or {})
            .get(resource_name, "0"))
            for c in (pod.get("spec") or {}).get("containers", [])))

    def _desired_for_metric(self, m: dict, pods: list[Obj], current: int
                            ) -> tuple[int, dict] | None:
        """One metric spec -> (desired replicas, status entry), or None
        when there are no samples (hold — upstream no-scale on missing
        metrics) or the spec is invalid."""
        if m.get("type") == "Resource":
            res = m.get("resource") or {}
            name = res.get("name", "cpu")
            target = res.get("target") or {}
            samples = [(self._get_metric(p, name),
                        self._pod_request(p, name)) for p in pods]
            samples = [(u, r) for u, r in samples if u is not None]
            if not samples:
                return None
            if target.get("type") == "AverageValue" or \
                    "averageValue" in target:
                # same units as the metrics getter: milliCPU / bytes
                parse = (quantity.parse_cpu_milli if name == "cpu"
                         else quantity.parse_mem_bytes)
                want = float(parse(str(target.get("averageValue", 0))))
                if want <= 0:
                    return None
                avg = sum(u for u, _ in samples) / len(samples)
                ratio = avg / want
                cur_val = avg
                status = {"type": "Resource", "resource": {
                    "name": name, "current": {"averageValue": avg}}}
            else:
                pct = target.get("averageUtilization", 80)
                if not isinstance(pct, (int, float)) or pct <= 0:
                    return None
                utils = [100.0 * u / r for u, r in samples if r > 0]
                if not utils:
                    return None
                avg = sum(utils) / len(utils)
                ratio = avg / pct
                cur_val = avg
                status = {"type": "Resource", "resource": {
                    "name": name,
                    "current": {"averageUtilization": int(avg)}}}
        elif m.get("type") == "Pods":
            pm = m.get("pods") or {}
            name = (pm.get("metric") or {}).get("name", "")
            want = float(quantity.parse_quantity(
                str((pm.get("target") or {}).get("averageValue", 0))))
            if not name or want <= 0:
                return None
            samples = [self._get_metric(p, name) for p in pods]
            samples = [s for s in samples if s is not None]
            if not samples:
                return None
            avg = sum(samples) / len(samples)
            ratio = avg / want
            cur_val = avg
            status = {"type": "Pods", "pods": {
                "metric": {"name": name},
                "current": {"averageValue": avg}}}
        else:
            return None
        # tolerance: don't scale on noise (horizontal.go:806)
        if abs(ratio - 1.0) <= TOLERANCE:
            desired = current
        else:
            import math
            desired = max(1, math.ceil(current * ratio - 1e-9))
        return desired, status

    # -- behavior (normalizeDesiredReplicasWithBehaviors) ----------------

    @staticmethod
    def _policy_limit(policies: list[dict], events: list[tuple[float, int]],
                      current: int, now: float, up: bool,
                      select: str) -> int | None:
        """Replica bound allowed by the scaling policies, None = no limit
        (or Disabled -> current, i.e. no change in that direction).
        Only events in THIS direction consume policy budget (upstream
        keeps separate scaleUpEvents/scaleDownEvents for the same
        reason — an opposite-direction event must not inflate room)."""
        if select == "Disabled":
            return current
        if not policies:
            return None
        bounds = []
        for pol in policies:
            period = pol.get("periodSeconds", 60)
            changed = sum((d if up else -d) for t, d in events
                          if now - t <= period
                          and (d > 0) == up)
            if pol.get("type") == "Percent":
                allowed = int(current * pol.get("value", 100) / 100.0) or 1
            else:  # Pods
                allowed = pol.get("value", 4)
            room = max(0, allowed - changed)
            bounds.append(current + room if up else current - room)
        pick = max if (up == (select != "Min")) else min
        return pick(bounds)

    def _sync_one(self, hpa: Obj, now: float) -> None:
        spec = hpa.get("spec") or {}
        ref = spec.get("scaleTargetRef") or {}
        resource = SCALE_TARGETS.get(ref.get("kind"))
        if resource is None:
            return
        ns, hpa_name = meta.namespace(hpa), meta.name(hpa)
        target = self.client.get(resource, ns, ref.get("name", ""))
        current = int((target.get("spec") or {}).get("replicas", 1))
        sel = ((target.get("spec") or {}).get("selector") or {}) \
            .get("matchLabels", {})
        pods = [p for p in self.pod_informer.list(ns)
                if sel and all(meta.labels(p).get(k) == v
                               for k, v in sel.items())
                and (p.get("status") or {}).get("phase")
                not in ("Succeeded", "Failed")]
        # multi-metric: MAX of per-metric desires (computeReplicasForMetrics)
        proposals, current_metrics = [], []
        for m in self._metric_specs(spec):
            got = self._desired_for_metric(m, pods, current)
            if got is not None:
                proposals.append(got[0])
                current_metrics.append(got[1])
        if not proposals:
            return  # no metric produced a sample: hold
        desired = max(proposals)
        lo = spec.get("minReplicas", 1)
        hi = spec.get("maxReplicas", max(lo, desired))
        desired = max(lo, min(hi, desired))
        key = f"{ns}/{hpa_name}"

        behavior = spec.get("behavior") or {}
        up_b = behavior.get("scaleUp") or {}
        down_b = behavior.get("scaleDown") or {}
        # stabilization: down acts on the window max, up on the window min
        recs = self._recommendations.setdefault(key, [])
        recs.append((now, desired))
        max_window = max(
            float(down_b.get("stabilizationWindowSeconds",
                             self.downscale_stabilization)),
            float(up_b.get("stabilizationWindowSeconds", 0.0)))
        recs[:] = [(t, d) for t, d in recs if now - t <= max_window]
        if desired < current:
            win = float(down_b.get("stabilizationWindowSeconds",
                                   self.downscale_stabilization))
            desired = max(d for t, d in recs if now - t <= win)
        elif desired > current:
            win = float(up_b.get("stabilizationWindowSeconds", 0.0))
            desired = min(d for t, d in recs if now - t <= win)
        # behavior policies rate-limit the change
        events = self._scale_events.setdefault(key, [])
        events[:] = [(t, d) for t, d in events if now - t <= 3600.0]
        if desired > current:
            limit = self._policy_limit(
                up_b.get("policies") or [], events, current, now, up=True,
                select=up_b.get("selectPolicy", "Max"))
            if limit is not None:
                desired = min(desired, max(limit, current))
        elif desired < current:
            limit = self._policy_limit(
                down_b.get("policies") or [], events, current, now,
                up=False, select=down_b.get("selectPolicy", "Max"))
            if limit is not None:
                desired = max(desired, min(limit, current))
        desired = max(lo, min(hi, desired))

        if desired != current:
            def patch(o):
                o.setdefault("spec", {})["replicas"] = desired
                return o
            self.client.guaranteed_update(resource, ns, ref["name"], patch)
            events.append((now, desired - current))
        status = {"currentReplicas": current, "desiredReplicas": desired,
                  "currentMetrics": current_metrics,
                  "lastScaleTime": now if desired != current
                  else (hpa.get("status") or {}).get("lastScaleTime")}
        # v1 status compatibility: surface cpu utilization when present
        for cm in current_metrics:
            cur = (cm.get("resource") or {})
            if cur.get("name") == "cpu" and "averageUtilization" in \
                    (cur.get("current") or {}):
                status["currentCPUUtilizationPercentage"] = \
                    cur["current"]["averageUtilization"]
        def spatch(o):
            o["status"] = status
            return o
        try:
            self.client.guaranteed_update(HPAS, ns, hpa_name, spatch)
        except kv.NotFoundError:
            pass
