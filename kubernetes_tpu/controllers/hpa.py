"""Horizontal Pod Autoscaler controller.

Reference: pkg/controller/podautoscaler/ — the classic ratio algorithm:
desired = ceil(current * currentMetricValue / targetMetricValue), clamped
to [minReplicas, maxReplicas], with a scale-down stabilization window.

There is no metrics-server in this stack; pod usage comes from a pluggable
metrics getter.  The default reads the pod annotation
``metrics.kubernetes.io/cpu-usage`` (milliCPU, stamped by the hollow
kubelet or tests) — the same seam upstream fills with the resource-metrics
API.  Targets: spec.targetCPUUtilizationPercentage (autoscaling/v1 shape)
against container CPU requests.
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta, quantity
from ..api.meta import Obj
from ..client.clientset import HPAS, PODS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv

logger = logging.getLogger(__name__)

USAGE_ANNOTATION = "metrics.kubernetes.io/cpu-usage"

SCALE_TARGETS = {"Deployment": "deployments", "ReplicaSet": "replicasets",
                 "StatefulSet": "statefulsets"}


def default_metrics_getter(pod: Obj) -> float | None:
    """-> milliCPU in use, or None if no sample."""
    raw = (pod["metadata"].get("annotations") or {}).get(USAGE_ANNOTATION)
    if raw is None:
        return None
    try:
        return float(quantity.parse_cpu_milli(raw))
    except (ValueError, TypeError):
        return None


class HorizontalPodAutoscaler:
    name = "horizontalpodautoscaler"

    def __init__(self, client: Client, factory: SharedInformerFactory,
                 tick: float = 15.0, metrics_getter=default_metrics_getter,
                 downscale_stabilization: float = 300.0):
        self.client = client
        self.hpa_informer = factory.informer(HPAS)
        self.pod_informer = factory.informer(PODS)
        self.tick = tick
        self.metrics_getter = metrics_getter
        self.downscale_stabilization = downscale_stabilization
        self._recommendations: dict[str, list[tuple[float, int]]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick):
            try:
                self.reconcile_once(time.time())
            except Exception:  # noqa: BLE001
                logger.exception("hpa reconcile failed")

    def reconcile_once(self, now: float) -> None:
        live = set()
        for hpa in self.hpa_informer.list(None):
            live.add(meta.namespaced_name(hpa))
            try:
                self._sync_one(hpa, now)
            except Exception as e:  # noqa: BLE001 — one bad HPA must not
                logger.warning("hpa %s: %s", meta.namespaced_name(hpa), e)
        # drop stabilization windows of deleted HPAs
        for key in list(self._recommendations):
            if key not in live:
                del self._recommendations[key]

    def _sync_one(self, hpa: Obj, now: float) -> None:
        spec = hpa.get("spec") or {}
        ref = spec.get("scaleTargetRef") or {}
        resource = SCALE_TARGETS.get(ref.get("kind"))
        if resource is None:
            return
        ns, hpa_name = meta.namespace(hpa), meta.name(hpa)
        target = self.client.get(resource, ns, ref.get("name", ""))
        current = int((target.get("spec") or {}).get("replicas", 1))
        sel = ((target.get("spec") or {}).get("selector") or {}) \
            .get("matchLabels", {})
        pods = [p for p in self.pod_informer.list(ns)
                if sel and all(meta.labels(p).get(k) == v
                               for k, v in sel.items())
                and (p.get("status") or {}).get("phase")
                not in ("Succeeded", "Failed")]
        target_pct = spec.get("targetCPUUtilizationPercentage", 80)
        if not isinstance(target_pct, (int, float)) or target_pct <= 0:
            logger.warning("hpa %s/%s: invalid target %r", ns, hpa_name,
                           target_pct)
            return
        utilizations = []
        for p in pods:
            usage = self.metrics_getter(p)
            if usage is None:
                continue
            request = sum(quantity.parse_cpu_milli(
                ((c.get("resources") or {}).get("requests") or {})
                .get("cpu", "0"))
                for c in (p.get("spec") or {}).get("containers", []))
            if request > 0:
                utilizations.append(100.0 * usage / request)
        if not utilizations:
            return  # no samples: hold (upstream: no-scale on missing metrics)
        avg = sum(utilizations) / len(utilizations)
        desired = max(1, -(-int(current * avg) // int(target_pct)))  # ceil
        lo = spec.get("minReplicas", 1)
        hi = spec.get("maxReplicas", max(lo, desired))
        desired = max(lo, min(hi, desired))
        key = f"{ns}/{hpa_name}"
        # scale-down stabilization: act on the max recommendation in window
        recs = self._recommendations.setdefault(key, [])
        recs.append((now, desired))
        recs[:] = [(t, d) for t, d in recs
                   if now - t <= self.downscale_stabilization]
        if desired < current:
            desired = max(d for _, d in recs)
        if desired != current:
            def patch(o):
                o.setdefault("spec", {})["replicas"] = desired
                return o
            self.client.guaranteed_update(resource, ns, ref["name"], patch)
        status = {"currentReplicas": current, "desiredReplicas": desired,
                  "currentCPUUtilizationPercentage": int(avg),
                  "lastScaleTime": now if desired != current
                  else (hpa.get("status") or {}).get("lastScaleTime")}
        def spatch(o):
            o["status"] = status
            return o
        try:
            self.client.guaranteed_update(HPAS, ns, hpa_name, spatch)
        except kv.NotFoundError:
            pass
