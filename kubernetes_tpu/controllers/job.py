"""Job controller.

Reference: pkg/controller/job/ — syncJob: keep `parallelism` active pods
until `completions` pods have Succeeded; failed pods are retried up to
backoffLimit; on completion set the Complete condition, on exhaustion
Failed.
"""

from __future__ import annotations

import logging
import time
import uuid

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import JOBS, PODS
from ..store import kv
from .base import Controller, is_owned_by, owner_ref, split_key
from .replicaset import pod_is_active

logger = logging.getLogger(__name__)


class JobController(Controller):
    name = "job"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.job_informer = factory.informer(JOBS)
        self.pod_informer = factory.informer(PODS)
        self.job_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, pod: Obj, old) -> None:
        ref = meta.controller_ref(pod)
        if ref and ref.get("kind") == "Job":
            self.enqueue_key(f"{meta.namespace(pod)}/{ref['name']}")

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        job = self.job_informer.get(ns, name)
        if job is None:
            return
        spec = job.get("spec") or {}
        completions = spec.get("completions", 1)
        parallelism = spec.get("parallelism", 1)
        backoff_limit = spec.get("backoffLimit", 6)

        owned = [p for p in self.pod_informer.list(ns) if is_owned_by(p, job)]
        succeeded = sum(1 for p in owned
                        if (p.get("status") or {}).get("phase") == "Succeeded")
        failed = sum(1 for p in owned
                     if (p.get("status") or {}).get("phase") == "Failed")
        active = [p for p in owned if pod_is_active(p)]

        conds = (job.get("status") or {}).get("conditions") or []
        done = any(c.get("type") in ("Complete", "Failed") for c in conds)

        if not done:
            if succeeded >= completions:
                conds = [{"type": "Complete", "status": "True"}]
                for p in active:  # completions reached: reap stragglers
                    try:
                        self.client.delete(PODS, ns, meta.name(p))
                    except kv.NotFoundError:
                        pass
                active = []
            elif failed > backoff_limit:
                conds = [{"type": "Failed", "status": "True",
                          "reason": "BackoffLimitExceeded"}]
            else:
                want_active = min(parallelism, completions - succeeded)
                for _ in range(want_active - len(active)):
                    self._create_pod(job)

        status = {"active": len(active), "succeeded": succeeded,
                  "failed": failed, "conditions": conds}
        if (job.get("status") or {}) != status:
            def patch(o):
                o["status"] = status
                return o
            try:
                self.client.guaranteed_update(JOBS, ns, name, patch)
            except kv.NotFoundError:
                pass

    def _create_pod(self, job: Obj) -> None:
        tmpl = (job.get("spec") or {}).get("template") or {}
        ns = meta.namespace(job)
        pod = meta.new_object("Pod", f"{meta.name(job)}-{uuid.uuid4().hex[:5]}", ns)
        tmpl_meta = tmpl.get("metadata") or {}
        pod["metadata"]["labels"] = dict(tmpl_meta.get("labels") or {})
        if tmpl_meta.get("annotations"):
            pod["metadata"]["annotations"] = dict(tmpl_meta["annotations"])
        pod["metadata"]["ownerReferences"] = [owner_ref(job, "Job")]
        pod["spec"] = meta.deep_copy(tmpl.get("spec") or {"containers": [
            {"name": "c0", "image": "img"}]})
        pod["spec"].setdefault("restartPolicy", "Never")
        pod["spec"].setdefault("schedulerName", "default-scheduler")
        try:
            self.client.create(PODS, pod)
        except kv.AlreadyExistsError:
            pass
