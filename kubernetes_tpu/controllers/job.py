"""Job controller.

Reference: pkg/controller/job/ — syncJob: keep `parallelism` active pods
until `completions` pods have Succeeded; failed pods are retried up to
backoffLimit; on completion set the Complete condition, on exhaustion
Failed.
"""

from __future__ import annotations

import logging
import time
import uuid

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import JOBS, PODS
from ..store import kv
from .base import Controller, Expectations, is_owned_by, owner_ref, split_key
from .replicaset import pod_is_active

logger = logging.getLogger(__name__)


class JobController(Controller):
    name = "job"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.job_informer = factory.informer(JOBS)
        self.pod_informer = factory.informer(PODS)
        self.expectations = Expectations()
        self.job_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, pod: Obj, old) -> None:
        ref = meta.controller_ref(pod)
        if ref and ref.get("kind") == "Job":
            key = f"{meta.namespace(pod)}/{ref['name']}"
            if type_ == kv.ADDED:
                self.expectations.creation_observed(key)
            elif type_ == kv.DELETED:
                self.expectations.deletion_observed(key)
            self.enqueue_key(key)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        job = self.job_informer.get(ns, name)
        if job is None:
            return
        spec = job.get("spec") or {}
        completions = spec.get("completions", 1)
        parallelism = spec.get("parallelism", 1)
        backoff_limit = spec.get("backoffLimit", 6)

        owned = [p for p in self.pod_informer.list(ns) if is_owned_by(p, job)]
        succeeded = sum(1 for p in owned
                        if (p.get("status") or {}).get("phase") == "Succeeded")
        failed = sum(1 for p in owned
                     if (p.get("status") or {}).get("phase") == "Failed")
        active = [p for p in owned if pod_is_active(p)]

        conds = (job.get("status") or {}).get("conditions") or []
        done = any(c.get("type") in ("Complete", "Failed") for c in conds)

        if not done and self.expectations.satisfied(key):
            if succeeded >= completions:
                conds = [{"type": "Complete", "status": "True"}]
                for p in active:  # completions reached: reap stragglers
                    try:
                        self.client.delete(PODS, ns, meta.name(p))
                    except kv.NotFoundError:
                        pass
                active = []
            elif failed > backoff_limit:
                conds = [{"type": "Failed", "status": "True",
                          "reason": "BackoffLimitExceeded"}]
            else:
                want_active = min(parallelism, completions - succeeded)
                n_new = want_active - len(active)
                if n_new > 0:
                    self.expectations.expect_creations(key, n_new)
                    for i in range(n_new):
                        try:
                            if not self._create_pod(job):
                                self.expectations.creation_observed(key)
                        except Exception:
                            # lower this + all remaining uncreated slots so
                            # the retry isn't gated for TIMEOUT (the
                            # reference's slowStartBatch does the same)
                            for _ in range(n_new - i):
                                self.expectations.creation_observed(key)
                            raise
        elif not done:
            # expectations pending: leave children alone this round
            pass

        status = {"active": len(active), "succeeded": succeeded,
                  "failed": failed, "conditions": conds}
        prev = job.get("status") or {}
        if any(c.get("type") in ("Complete", "Failed") for c in conds):
            # own the completion stamp so status rewrites don't wipe it
            # (the ttl-after-finished controller keys its sweep off this)
            status["completionTime"] = prev.get("completionTime",
                                                time.time())
        if prev != status:
            def patch(o):
                o["status"] = status
                return o
            try:
                self.client.guaranteed_update(JOBS, ns, name, patch)
            except kv.NotFoundError:
                pass

    def _create_pod(self, job: Obj) -> None:
        tmpl = (job.get("spec") or {}).get("template") or {}
        ns = meta.namespace(job)
        pod = meta.new_object("Pod", f"{meta.name(job)}-{uuid.uuid4().hex[:5]}", ns)
        tmpl_meta = tmpl.get("metadata") or {}
        pod["metadata"]["labels"] = dict(tmpl_meta.get("labels") or {})
        if tmpl_meta.get("annotations"):
            pod["metadata"]["annotations"] = dict(tmpl_meta["annotations"])
        pod["metadata"]["ownerReferences"] = [owner_ref(job, "Job")]
        pod["spec"] = meta.deep_copy(tmpl.get("spec") or {"containers": [
            {"name": "c0", "image": "img"}]})
        pod["spec"].setdefault("restartPolicy", "Never")
        pod["spec"].setdefault("schedulerName", "default-scheduler")
        try:
            self.client.create(PODS, pod)
            return True
        except kv.AlreadyExistsError:
            return False
