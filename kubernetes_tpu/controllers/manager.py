"""Controller manager: runs the controller fleet behind leader election.

Reference: cmd/kube-controller-manager/app/controllermanager.go:425-467 —
one process, shared informer factory, leader-elected, each controller with
its own workqueue + workers.
"""

from __future__ import annotations

import logging
import threading

from ..client.clientset import Client
from ..client.informer import SharedInformerFactory
from ..client.leaderelection import LeaderElector
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .garbagecollector import GarbageCollector
from .storageversion import StorageVersionGC
from .hpa import HorizontalPodAutoscaler
from .job import JobController
from .namespace import NamespaceController
from .nodelifecycle import NodeLifecycleController
from .podgc import PodGCController
from .replicaset import ReplicaSetController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController
from .statefulset import StatefulSetController
from .bootstrap import BootstrapSigner, TokenCleaner
from .certificates import (
    CSRApprovingController, CSRCleanerController, CSRSigningController,
)
from .endpointslice import EndpointSliceController
from .nodeipam import NodeIpamController
from .replication import ReplicationControllerController
from .rootca import RootCACertPublisher
from .ttl import TTLController
from .ttlafterfinished import TTLAfterFinishedController
from .clusterroleaggregation import ClusterRoleAggregationController
from .endpointslicemirroring import EndpointSliceMirroringController
from .volume import (
    AttachDetachController, EphemeralVolumeController,
    PersistentVolumeController, PVCProtectionController,
    PVProtectionController, VolumeExpandController,
)

logger = logging.getLogger(__name__)

# startup list mirrors controllermanager.go:425-467; bootstrapsigner and
# tokencleaner are registered but off by default, same as the reference
# (controllermanager.go ControllersDisabledByDefault); nodeipam is gated on
# --allocate-node-cidrs there, off by default here too
DEFAULT_CONTROLLERS = ("deployment", "replicaset", "statefulset", "daemonset",
                       "job", "cronjob", "garbagecollector", "nodelifecycle",
                       "disruption", "namespace", "resourcequota",
                       "serviceaccount", "podgc", "ttlafterfinished",
                       "horizontalpodautoscaler", "endpointslice",
                       "replicationcontroller", "csrapproving", "csrsigning",
                       "csrcleaner", "ttl", "root-ca-cert-publisher",
                       "persistentvolume-binder", "pvc-protection",
                       "pv-protection", "attachdetach", "ephemeral-volume",
                       "storage-version-gc", "clusterrole-aggregation",
                       "endpointslicemirroring", "persistentvolume-expander")


class ControllerManager:
    # name -> constructor; the complete registry (controllermanager.go's
    # NewControllerInitializers).  Class-level so tooling/tests can audit
    # that every controller is wired without instantiating anything.
    CTORS = {
            "deployment": DeploymentController,
            "replicaset": ReplicaSetController,
            "statefulset": StatefulSetController,
            "daemonset": DaemonSetController,
            "job": JobController,
            "cronjob": CronJobController,
            "garbagecollector": GarbageCollector,
            "nodelifecycle": NodeLifecycleController,
            "disruption": DisruptionController,
            "namespace": NamespaceController,
            "resourcequota": ResourceQuotaController,
            "serviceaccount": ServiceAccountController,
            "podgc": PodGCController,
            "ttlafterfinished": TTLAfterFinishedController,
            "horizontalpodautoscaler": HorizontalPodAutoscaler,
            "endpointslice": EndpointSliceController,
            "replicationcontroller": ReplicationControllerController,
            "csrapproving": CSRApprovingController,
            "csrsigning": CSRSigningController,
            "csrcleaner": CSRCleanerController,
            "ttl": TTLController,
            "root-ca-cert-publisher": RootCACertPublisher,
            "storage-version-gc": StorageVersionGC,
            "persistentvolume-binder": PersistentVolumeController,
            "pvc-protection": PVCProtectionController,
            "pv-protection": PVProtectionController,
            "attachdetach": AttachDetachController,
            "ephemeral-volume": EphemeralVolumeController,
            "clusterrole-aggregation": ClusterRoleAggregationController,
            "endpointslicemirroring": EndpointSliceMirroringController,
            "persistentvolume-expander": VolumeExpandController,
            # registered but disabled by default (reference parity):
            "nodeipam": NodeIpamController,
            "tokencleaner": TokenCleaner,
            "bootstrapsigner": BootstrapSigner,
    }

    def __init__(self, client: Client, factory: SharedInformerFactory,
                 controllers: tuple[str, ...] = DEFAULT_CONTROLLERS,
                 leader_elect: bool = False, identity: str | None = None):
        self.client = client
        self.factory = factory
        self.controllers: dict[str, object] = {}
        for name in controllers:
            try:
                self.controllers[name] = self.CTORS[name](client, factory)
            except ModuleNotFoundError as e:
                # optional-dependency gate (e.g. the CSR signer needs the
                # cryptography package): run degraded rather than not at all
                logger.warning("controller %s disabled: %s", name, e)
        self._elector: LeaderElector | None = None
        self._leader_elect = leader_elect
        self._identity = identity
        self._running = False

    def run(self) -> None:
        if self._leader_elect:
            self._elector = LeaderElector(
                self.client, "kube-controller-manager",
                identity=self._identity,
                on_started_leading=self._start_all,
                on_stopped_leading=self._stop_all)
            self._elector.run()
        else:
            self._start_all()

    def _start_all(self) -> None:
        if self._running:
            return
        self._running = True
        for name, c in self.controllers.items():
            logger.info("starting controller %s", name)
            c.run()

    def _stop_all(self) -> None:
        if not self._running:
            return
        self._running = False
        for c in self.controllers.values():
            c.stop()

    def stop(self) -> None:
        if self._elector:
            self._elector.stop()
        self._stop_all()
