"""Namespace controller.

Reference: pkg/controller/namespace/ — when a namespace is deleted, every
namespaced object inside it is deleted (content finalization), then the
kubernetes finalizer is removed.  Our store deletes the namespace object
immediately, so the controller reacts to the DELETED event and sweeps all
known namespaced resources; it also sets status.phase on live namespaces.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.meta import Obj
from ..client import clientset as cs
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)

# the namespaced resource sweep list (namespace_controller discovers these
# via the discovery API; ours is static like the rest of the type system)
NAMESPACED_RESOURCES = (
    cs.PODS, cs.SERVICES, cs.ENDPOINTS, cs.REPLICASETS, cs.DEPLOYMENTS,
    cs.JOBS, cs.CRONJOBS, cs.STATEFULSETS, cs.DAEMONSETS, cs.CONFIGMAPS,
    cs.SECRETS, cs.PVCS, cs.PDBS, cs.PODGROUPS, cs.RESOURCEQUOTAS,
    cs.SERVICEACCOUNTS, cs.LIMITRANGES, cs.HPAS, cs.LEASES, cs.EVENTS,
    cs.ENDPOINTSLICES, cs.REPLICATIONCONTROLLERS,
)


class NamespaceController(Controller):
    name = "namespace"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.ns_informer = factory.informer(cs.NAMESPACES)
        self.ns_informer.add_event_handler(self._on_ns)
        self._deleted: set[str] = set()

    def _on_ns(self, type_, ns_obj: Obj, old) -> None:
        name = meta.name(ns_obj)
        if type_ == kv.DELETED:
            self._deleted.add(name)
        self.enqueue_key(name)

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        ns_obj = self.ns_informer.get("", name)
        if ns_obj is None:
            if name in self._deleted:
                self._sweep(name)
                self._deleted.discard(name)
            return
        # live namespace: ensure Active phase
        phase = (ns_obj.get("status") or {}).get("phase")
        want = "Terminating" if meta.deletion_timestamp(ns_obj) else "Active"
        if phase != want:
            def patch(o):
                o.setdefault("status", {})["phase"] = want
                return o
            try:
                self.client.guaranteed_update(cs.NAMESPACES, "", name, patch)
            except kv.NotFoundError:
                pass
        if want == "Terminating":
            self._sweep(name)

    def _sweep(self, namespace: str) -> None:
        """Delete all content of the namespace (deleteAllContent)."""
        for resource in NAMESPACED_RESOURCES:
            try:
                items, _ = self.client.list(resource, namespace)
            except Exception:  # noqa: BLE001 — resource table may not exist
                continue
            for obj in items:
                try:
                    self.client.delete(resource, namespace, meta.name(obj))
                except kv.NotFoundError:
                    pass
