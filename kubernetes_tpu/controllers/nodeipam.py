"""NodeIPAM controller.

Reference: pkg/controller/nodeipam/ (range_allocator.go) — carves the
cluster CIDR into fixed-size per-node pod CIDRs and writes
node.spec.podCIDR/podCIDRs on registration; released when the node goes.
Allocation state is an in-memory bitmap rebuilt from informer state on
start (the reference's cidrset.CidrSet).
"""

from __future__ import annotations

import ipaddress
import logging
import threading

from ..api import meta
from ..client.clientset import NODES
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)


class CidrSet:
    """Bitmap allocator over cluster_cidr split at node_mask (cidrset.go)."""

    def __init__(self, cluster_cidr: str = "10.244.0.0/16",
                 node_mask: int = 24):
        self.net = ipaddress.ip_network(cluster_cidr)
        self.node_mask = node_mask
        self.subnets = list(self.net.subnets(new_prefix=node_mask))
        self._used: dict[str, int] = {}   # cidr -> subnet index
        self._free = set(range(len(self.subnets)))
        self._lock = threading.Lock()

    def allocate(self) -> str | None:
        with self._lock:
            if not self._free:
                return None
            i = min(self._free)
            self._free.discard(i)
            cidr = str(self.subnets[i])
            self._used[cidr] = i
            return cidr

    def occupy(self, cidr: str) -> None:
        with self._lock:
            i = self._used.get(cidr)
            if i is None:
                try:
                    i = self.subnets.index(ipaddress.ip_network(cidr))
                except ValueError:
                    return  # outside our range (reference logs + skips)
                self._used[cidr] = i
                self._free.discard(i)

    def release(self, cidr: str) -> None:
        with self._lock:
            i = self._used.pop(cidr, None)
            if i is not None:
                self._free.add(i)


class NodeIpamController(Controller):
    name = "nodeipam"

    def __init__(self, client, factory, cluster_cidr: str = "10.244.0.0/16",
                 node_mask: int = 24):
        super().__init__(client, factory)
        self.cidrs = CidrSet(cluster_cidr, node_mask)
        self.node_informer = factory.informer(NODES)
        # rebuild occupancy from informer state before handling events
        for n in self.node_informer.list(None):
            cidr = (n.get("spec") or {}).get("podCIDR")
            if cidr:
                self.cidrs.occupy(cidr)
        self.node_informer.add_event_handler(self._on_node)

    def _on_node(self, type_, node, old) -> None:
        if type_ == kv.DELETED:
            cidr = (node.get("spec") or {}).get("podCIDR")
            if cidr:
                self.cidrs.release(cidr)
            return
        self.enqueue(node)

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        node = self.node_informer.get("", name)
        if node is None:
            return
        if (node.get("spec") or {}).get("podCIDR"):
            self.cidrs.occupy(node["spec"]["podCIDR"])
            return
        cidr = self.cidrs.allocate()
        if cidr is None:
            logger.error("nodeipam: cluster CIDR exhausted for node %s", name)
            return
        ok = False
        try:
            def patch(o):
                spec = o.setdefault("spec", {})
                if not spec.get("podCIDR"):
                    spec["podCIDR"] = cidr
                    spec["podCIDRs"] = [cidr]
                return o
            updated = self.client.guaranteed_update(NODES, "", name, patch)
            ok = (updated.get("spec") or {}).get("podCIDR") == cidr
        except kv.NotFoundError:
            pass
        finally:
            if not ok:
                self.cidrs.release(cidr)
