"""Node lifecycle controller.

Reference: pkg/controller/nodelifecycle/ — monitors node heartbeats (Lease
renewTime + node status); a node missing heartbeats past the grace period
is marked NotReady and tainted unreachable; its pods are evicted (deleted)
after the eviction grace so their controllers reschedule them elsewhere.
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import LEASES, NODES, PODS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv

logger = logging.getLogger(__name__)

UNREACHABLE_TAINT = {"key": "node.kubernetes.io/unreachable",
                     "effect": "NoExecute"}
# admission (TaintNodesByCondition) starts every node with this taint;
# this controller lifts it on the first Ready observation and restores
# it while the node is NotReady (pkg/controller/nodelifecycle
# taint-based eviction's condition->taint mapping)
NOT_READY_TAINT = {"key": "node.kubernetes.io/not-ready",
                   "effect": "NoSchedule"}


class NodeLifecycleController:
    """Periodic monitor (not queue-driven: liveness is time-based)."""

    name = "nodelifecycle"

    def __init__(self, client: Client, factory: SharedInformerFactory,
                 grace_period: float = 40.0, tick: float = 5.0):
        self.client = client
        self.node_informer = factory.informer(NODES)
        self.pod_informer = factory.informer(PODS)
        self.grace = grace_period
        self.tick = tick
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick):
            try:
                self._monitor()
            except Exception:  # noqa: BLE001
                logger.exception("nodelifecycle monitor failed")

    def _heartbeat(self, node: Obj) -> float:
        try:
            lease = self.client.get(LEASES, "kube-node-lease", meta.name(node))
            return (lease.get("spec") or {}).get("renewTime", 0.0)
        except kv.NotFoundError:
            # fall back to the node's own status heartbeat
            return (node.get("status") or {}).get("lastHeartbeatTime", 0.0)

    def _monitor(self) -> None:
        now = time.time()
        for node in self.node_informer.list():
            hb = self._heartbeat(node)
            if hb == 0.0:
                continue  # never heartbeated: likely a synthetic/test node
            name = meta.name(node)
            ready = self._is_ready(node)
            if now - hb > self.grace:
                if ready:
                    logger.info("node %s missed heartbeats; marking NotReady", name)
                    self._set_ready(node, False)
                self._evict_pods(name)
            elif not ready:
                logger.info("node %s heartbeat recovered; marking Ready", name)
                self._set_ready(node, True)
            elif any(t.get("key") == NOT_READY_TAINT["key"]
                     for t in (node.get("spec") or {}).get("taints") or ()):
                # Ready and heartbeating but still carrying the
                # admission-time not-ready taint: lift it (the node's
                # first transition into service)
                self._set_ready(node, True)

    @staticmethod
    def _is_ready(node: Obj) -> bool:
        conds = (node.get("status") or {}).get("conditions") or []
        for c in conds:
            if c.get("type") == "Ready":
                return c.get("status") == "True"
        return True

    def _set_ready(self, node: Obj, ready: bool) -> None:
        def patch(n):
            conds = n.setdefault("status", {}).setdefault("conditions", [])
            conds[:] = [c for c in conds if c.get("type") != "Ready"]
            conds.append({"type": "Ready",
                          "status": "True" if ready else "False"})
            taints = n.setdefault("spec", {}).setdefault("taints", [])
            taints[:] = [t for t in taints
                         if t.get("key") not in (UNREACHABLE_TAINT["key"],
                                                 NOT_READY_TAINT["key"])]
            if not ready:
                taints.append(dict(UNREACHABLE_TAINT))
                taints.append(dict(NOT_READY_TAINT))
            return n
        try:
            self.client.guaranteed_update(NODES, "", meta.name(node), patch)
        except kv.NotFoundError:
            pass

    def _evict_pods(self, node_name: str) -> None:
        for pod in self.pod_informer.list():
            if meta.pod_node_name(pod) != node_name:
                continue
            tolerates = any(
                t.get("key") == UNREACHABLE_TAINT["key"]
                for t in (pod.get("spec") or {}).get("tolerations") or ())
            if tolerates:
                continue
            logger.info("evicting pod %s from unreachable node %s",
                        meta.namespaced_name(pod), node_name)
            try:
                self.client.delete(PODS, meta.namespace(pod), meta.name(pod))
            except kv.NotFoundError:
                pass
