"""Pod garbage collector.

Reference: pkg/controller/podgc/ — periodic sweep that deletes:
(1) terminated pods (Succeeded/Failed) beyond terminated-pod-gc-threshold,
oldest first; (2) pods bound to nodes that no longer exist; (3) unscheduled
pods marked for deletion.
"""

from __future__ import annotations

import logging
import threading

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import NODES, PODS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv

logger = logging.getLogger(__name__)


class PodGCController:
    name = "podgc"

    def __init__(self, client: Client, factory: SharedInformerFactory,
                 terminated_pod_threshold: int = 12500, tick: float = 20.0):
        self.client = client
        self.pod_informer = factory.informer(PODS)
        self.node_informer = factory.informer(NODES)
        self.threshold = terminated_pod_threshold
        self.tick = tick
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick):
            try:
                self.gc_once()
            except Exception:  # noqa: BLE001
                logger.exception("podgc sweep failed")

    def gc_once(self) -> None:
        pods = self.pod_informer.list(None)
        nodes = {meta.name(n) for n in self.node_informer.list(None)}
        self._gc_terminated(pods)
        self._gc_orphaned(pods, nodes)
        self._gc_unscheduled_terminating(pods)

    def _gc_terminated(self, pods: list[Obj]) -> None:
        terminated = [p for p in pods
                      if (p.get("status") or {}).get("phase")
                      in ("Succeeded", "Failed")]
        excess = len(terminated) - self.threshold
        if excess <= 0:
            return
        terminated.sort(key=meta.creation_timestamp)
        for p in terminated[:excess]:
            self._delete(p)

    def _gc_orphaned(self, pods: list[Obj], nodes: set[str]) -> None:
        for p in pods:
            node = meta.pod_node_name(p)
            if node and node not in nodes:
                self._delete(p)

    def _gc_unscheduled_terminating(self, pods: list[Obj]) -> None:
        for p in pods:
            if (meta.deletion_timestamp(p) is not None
                    and not meta.pod_node_name(p)):
                self._delete(p)

    def _delete(self, pod: Obj) -> None:
        try:
            self.client.delete(PODS, meta.namespace(pod), meta.name(pod))
        except kv.NotFoundError:
            pass
