"""ReplicaSet controller.

Reference: pkg/controller/replicaset/replica_set.go
  syncReplicaSet (:660): list owned pods via selector + controllerRef
  adoption, diff against spec.replicas, slowStartBatch create / scored
  delete, update status (replicas/readyReplicas/availableReplicas).

Simplifications vs reference: no expectations cache (our informer delivery
is synchronous with the store, so the sync that follows a create/delete
already observes it); deletion picks unready-then-youngest pods.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.labels import selector_from_dict
from ..api.meta import Obj
from ..client.clientset import PODS, REPLICASETS
from ..store import kv
from .base import Controller, Expectations, is_owned_by, owner_ref, split_key

logger = logging.getLogger(__name__)


def pod_is_ready(pod: Obj) -> bool:
    phase = (pod.get("status") or {}).get("phase")
    if phase != "Running":
        return False
    conds = (pod.get("status") or {}).get("conditions") or []
    return any(c.get("type") == "Ready" and c.get("status") == "True"
               for c in conds)


def pod_is_active(pod: Obj) -> bool:
    return (not meta.pod_is_terminal(pod)
            and meta.deletion_timestamp(pod) is None)


class ReplicaSetController(Controller):
    name = "replicaset"
    kind = "ReplicaSet"          # controllerRef kind owned pods carry
    resource = REPLICASETS       # status-update target resource

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.rs_informer = factory.informer(self.resource)
        self.pod_informer = factory.informer(PODS)
        self.expectations = Expectations()
        self.rs_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_: str, pod: Obj, old: Obj | None) -> None:
        ref = meta.controller_ref(pod)
        if ref and ref.get("kind") == self.kind:
            key = f"{meta.namespace(pod)}/{ref['name']}"
            if type_ == kv.ADDED:
                self.expectations.creation_observed(key)
            elif type_ == kv.DELETED:
                self.expectations.deletion_observed(key)
            self.enqueue_key(key)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        rs = self.rs_informer.get(ns, name)
        if rs is None:
            self.expectations.delete(key)
            return
        rs = self._normalize(rs)
        spec = rs.get("spec") or {}
        want = spec.get("replicas", 1)
        selector = selector_from_dict(spec.get("selector") or {})
        pods = [p for p in self.pod_informer.list(ns)
                if is_owned_by(p, rs) and pod_is_active(p)]
        # adoption: orphaned pods matching the selector
        for p in self.pod_informer.list(ns):
            if (not meta.owner_references(p) and pod_is_active(p)
                    and selector.matches(meta.labels(p))):
                self._adopt(p, rs)
                pods.append(p)

        diff = want - len(pods)
        if self.expectations.satisfied(key):
            if diff > 0:
                self.expectations.expect_creations(key, diff)
                for i in range(diff):
                    try:
                        if not self._create_pod(rs):
                            self.expectations.creation_observed(key)
                    except Exception:
                        # lower remaining slots so the retry isn't gated
                        # for TIMEOUT (slowStartBatch semantics)
                        for _ in range(diff - i):
                            self.expectations.creation_observed(key)
                        raise
            elif diff < 0:
                # prefer deleting not-ready, then youngest
                victims = sorted(pods, key=lambda p: (
                    pod_is_ready(p), meta.creation_timestamp(p)))[:(-diff)]
                self.expectations.expect_deletions(key, len(victims))
                for i, p in enumerate(victims):
                    try:
                        self.client.delete(PODS, ns, meta.name(p))
                    except kv.NotFoundError:
                        self.expectations.deletion_observed(key)
                    except Exception:
                        for _ in range(len(victims) - i):
                            self.expectations.deletion_observed(key)
                        raise
        self._update_status(rs, pods)

    def _normalize(self, rs: Obj) -> Obj:
        """Hook for subclasses reshaping the object before sync (RC)."""
        return rs

    def _adopt(self, pod: Obj, rs: Obj) -> None:
        def patch(p):
            p["metadata"].setdefault("ownerReferences", []).append(
                owner_ref(rs, self.kind))
            return p
        try:
            self.client.guaranteed_update(PODS, meta.namespace(pod),
                                          meta.name(pod), patch)
        except kv.StoreError:
            pass

    def _create_pod(self, rs: Obj) -> None:
        tmpl = (rs.get("spec") or {}).get("template") or {}
        ns = meta.namespace(rs)
        pod = meta.new_object("Pod", "", ns)
        pod["metadata"]["generateName"] = meta.name(rs) + "-"
        pod["metadata"]["name"] = f"{meta.name(rs)}-{meta.uid(rs)[:5]}-" + \
            __import__("uuid").uuid4().hex[:5]
        tmpl_meta = tmpl.get("metadata") or {}
        pod["metadata"]["labels"] = dict(tmpl_meta.get("labels") or {})
        if tmpl_meta.get("annotations"):
            pod["metadata"]["annotations"] = dict(tmpl_meta["annotations"])
        pod["metadata"]["ownerReferences"] = [owner_ref(rs, self.kind)]
        pod["spec"] = meta.deep_copy(tmpl.get("spec") or {"containers": [
            {"name": "c0", "image": "img"}]})
        pod["spec"].setdefault("schedulerName", "default-scheduler")
        try:
            self.client.create(PODS, pod)
            return True
        except kv.AlreadyExistsError:
            return False

    def _update_status(self, rs: Obj, pods: list[Obj]) -> None:
        ready = sum(1 for p in pods if pod_is_ready(p))
        status = {"replicas": len(pods), "readyReplicas": ready,
                  "availableReplicas": ready,
                  "observedGeneration": rs["metadata"].get("generation", 0)}
        if (rs.get("status") or {}) == status:
            return

        def patch(o):
            o["status"] = status
            return o
        try:
            self.client.guaranteed_update(self.resource, meta.namespace(rs),
                                          meta.name(rs), patch)
        except kv.NotFoundError:
            pass
