"""ReplicationController controller.

Reference: pkg/controller/replication/ — upstream literally adapts the
ReplicaSet controller over converted RC objects (replication_controller.go:
"It is actually just a wrapper around ReplicaSetController", conversion in
conversion.go).  Same here: ReplicaSetController parameterized over
kind/resource, plus the RC-specific selector shape — RC spec.selector is a
bare label map (no matchExpressions), defaulting to the template labels.
"""

from __future__ import annotations

from ..api.meta import Obj
from ..client.clientset import REPLICATIONCONTROLLERS
from .replicaset import ReplicaSetController


class ReplicationControllerController(ReplicaSetController):
    name = "replicationcontroller"
    kind = "ReplicationController"
    resource = REPLICATIONCONTROLLERS

    def _normalize(self, rc: Obj) -> Obj:
        spec = rc.get("spec") or {}
        sel = spec.get("selector") or (
            ((spec.get("template") or {}).get("metadata") or {}).get("labels")
            or {})
        shim = dict(rc)
        shim["spec"] = dict(spec, selector={"matchLabels": dict(sel)})
        return shim
