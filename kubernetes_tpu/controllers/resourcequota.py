"""ResourceQuota controller.

Reference: pkg/controller/resourcequota/ — recalculates each quota's
status.used from live objects whenever quota or pods change (plus a full
resync), so kubectl and the admission plugin see current usage.
"""

from __future__ import annotations

import logging

from ..api import meta, quantity
from ..api.meta import Obj
from ..client.clientset import PODS, RESOURCEQUOTAS
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)


class ResourceQuotaController(Controller):
    name = "resourcequota"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.rq_informer = factory.informer(RESOURCEQUOTAS)
        self.pod_informer = factory.informer(PODS)
        self.rq_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, pod: Obj, old) -> None:
        for rq in self.rq_informer.list(meta.namespace(pod)):
            self.enqueue(rq)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        rq = self.rq_informer.get(ns, name)
        if rq is None:
            return
        hard = (rq.get("spec") or {}).get("hard") or {}
        pods = [p for p in self.pod_informer.list(ns)
                if (p.get("status") or {}).get("phase")
                not in ("Succeeded", "Failed")]
        cpu = sum(self._cpu(p) for p in pods)
        mem = sum(self._mem(p) for p in pods)
        used = {}
        for k in hard:
            if k == "pods":
                used[k] = str(len(pods))
            elif k in ("cpu", "requests.cpu"):
                used[k] = quantity.format_cpu_milli(cpu)
            elif k in ("memory", "requests.memory"):
                used[k] = quantity.format_mem_bytes(mem)
        status = {"hard": dict(hard), "used": used}
        if (rq.get("status") or {}) != status:
            def patch(o):
                o["status"] = status
                return o
            try:
                self.client.guaranteed_update(RESOURCEQUOTAS, ns, name, patch)
            except kv.NotFoundError:
                pass

    @staticmethod
    def _cpu(pod) -> int:
        return sum(quantity.parse_cpu_milli(
            ((c.get("resources") or {}).get("requests") or {}).get("cpu", "0"))
            for c in (pod.get("spec") or {}).get("containers", []))

    @staticmethod
    def _mem(pod) -> int:
        return sum(quantity.parse_mem_bytes(
            ((c.get("resources") or {}).get("requests") or {})
            .get("memory", "0"))
            for c in (pod.get("spec") or {}).get("containers", []))
