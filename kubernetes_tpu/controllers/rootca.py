"""Root CA certificate publisher.

Reference: pkg/controller/certificates/rootcacertpublisher/publisher.go —
every Namespace gets a `kube-root-ca.crt` ConfigMap carrying the cluster
CA bundle (what pods mount to trust the apiserver); recreated on delete,
repaired on drift.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..client.clientset import CONFIGMAPS, NAMESPACES
from ..store import kv
from .base import Controller, split_key
from .certificates import ClusterCA

logger = logging.getLogger(__name__)

ROOT_CA_CONFIGMAP = "kube-root-ca.crt"


class RootCACertPublisher(Controller):
    name = "root-ca-cert-publisher"

    def __init__(self, client, factory, ca: ClusterCA | None = None):
        super().__init__(client, factory)
        self.ca_pem = (ca or ClusterCA.shared()).ca_pem()
        self.ns_informer = factory.informer(NAMESPACES)
        self.cm_informer = factory.informer(CONFIGMAPS)
        self.ns_informer.add_event_handler(
            lambda t, obj, old: self.enqueue_key(meta.name(obj)))
        self.cm_informer.add_event_handler(self._on_cm)

    def _on_cm(self, type_, cm, old) -> None:
        if meta.name(cm) == ROOT_CA_CONFIGMAP:
            self.enqueue_key(meta.namespace(cm))

    def sync(self, key: str) -> None:
        _, ns = split_key(key)
        if self.ns_informer.get("", ns) is None:
            return
        cm = self.cm_informer.get(ns, ROOT_CA_CONFIGMAP)
        if cm is None:
            obj = meta.new_object("ConfigMap", ROOT_CA_CONFIGMAP, ns)
            obj["data"] = {"ca.crt": self.ca_pem}
            try:
                self.client.create(CONFIGMAPS, obj)
            except kv.AlreadyExistsError:
                pass
            return
        if (cm.get("data") or {}).get("ca.crt") != self.ca_pem:
            def patch(o):
                o.setdefault("data", {})["ca.crt"] = self.ca_pem
                return o
            try:
                self.client.guaranteed_update(CONFIGMAPS, ns,
                                              ROOT_CA_CONFIGMAP, patch)
            except kv.NotFoundError:
                pass
