"""ServiceAccount + token controller.

Reference: pkg/controller/serviceaccount/ — every namespace gets a
"default" ServiceAccount; a token Secret is minted per ServiceAccount
(legacy token controller shape; modern kubelets use projected tokens, but
the API contract — secrets list on the SA — is what clients consume).
"""

from __future__ import annotations

import logging
import secrets as pysecrets

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import NAMESPACES, SECRETS, SERVICEACCOUNTS
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)


class ServiceAccountController(Controller):
    name = "serviceaccount"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.ns_informer = factory.informer(NAMESPACES)
        self.sa_informer = factory.informer(SERVICEACCOUNTS)
        self.ns_informer.add_event_handler(
            lambda t, obj, old: self.enqueue_key(meta.name(obj)))
        self.sa_informer.add_event_handler(self._on_sa)

    def _on_sa(self, type_, sa: Obj, old) -> None:
        self.enqueue_key(meta.namespace(sa))

    def sync(self, key: str) -> None:
        _, ns_name = split_key(key)
        if self.ns_informer.get("", ns_name) is None:
            return
        sa = self.sa_informer.get(ns_name, "default")
        if sa is None:
            obj = meta.new_object("ServiceAccount", "default", ns_name)
            try:
                sa = self.client.create(SERVICEACCOUNTS, obj)
            except kv.AlreadyExistsError:
                return
        # token secret (legacy token controller).  Read-through to the
        # store (not the informer, which may lag our own patch) and append
        # inside the CAS closure so a racing sync can't double-mint.
        try:
            sa = self.client.get(SERVICEACCOUNTS, ns_name, "default")
        except kv.NotFoundError:
            return
        if not sa.get("secrets"):
            token_name = f"default-token-{pysecrets.token_hex(3)}"
            minted = {"made": False}

            def patch(o):
                if o.get("secrets"):
                    return o  # another sync won the race
                o.setdefault("secrets", []).append({"name": token_name})
                minted["made"] = True
                return o
            try:
                self.client.guaranteed_update(SERVICEACCOUNTS, ns_name,
                                              "default", patch)
            except kv.NotFoundError:
                return
            if minted["made"]:
                secret = meta.new_object("Secret", token_name, ns_name)
                secret["type"] = "kubernetes.io/service-account-token"
                secret["metadata"]["annotations"] = {
                    "kubernetes.io/service-account.name": "default"}
                secret["data"] = {"token": pysecrets.token_urlsafe(32)}
                try:
                    self.client.create(SECRETS, secret)
                except kv.AlreadyExistsError:
                    pass
