"""StatefulSet controller.

Reference: pkg/controller/statefulset/ — stable identities: pods are named
<set>-0..<replicas-1>; OrderedReady management creates ordinal i+1 only
once ordinal i is running and ready, and scales down from the highest
ordinal; Parallel management creates/deletes all at once.  Each
volumeClaimTemplate yields a PVC <claim>-<pod> that survives pod deletion.
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import PODS, PVCS, STATEFULSETS
from ..store import kv
from .base import Controller, Expectations, is_owned_by, owner_ref, split_key
from .replicaset import pod_is_active, pod_is_ready

logger = logging.getLogger(__name__)


def ordinal_of(pod_name: str, set_name: str) -> int:
    suffix = pod_name[len(set_name) + 1:]
    try:
        return int(suffix)
    except ValueError:
        return -1


class StatefulSetController(Controller):
    name = "statefulset"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.set_informer = factory.informer(STATEFULSETS)
        self.pod_informer = factory.informer(PODS)
        self.expectations = Expectations()
        self.set_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, pod: Obj, old) -> None:
        ref = meta.controller_ref(pod)
        if ref and ref.get("kind") == "StatefulSet":
            key = f"{meta.namespace(pod)}/{ref['name']}"
            if type_ == kv.ADDED:
                self.expectations.creation_observed(key)
            elif type_ == kv.DELETED:
                self.expectations.deletion_observed(key)
            self.enqueue_key(key)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        sts = self.set_informer.get(ns, name)
        if sts is None:
            self.expectations.delete(key)
            return
        spec = sts.get("spec") or {}
        want = spec.get("replicas", 1)
        parallel = (spec.get("podManagementPolicy") == "Parallel")
        owned = {ordinal_of(meta.name(p), name): p
                 for p in self.pod_informer.list(ns)
                 if is_owned_by(p, sts) and pod_is_active(p)
                 and ordinal_of(meta.name(p), name) >= 0}

        if self.expectations.satisfied(key):
            self._manage(key, sts, ns, name, want, parallel, owned)

        ready = sum(1 for p in owned.values() if pod_is_ready(p))
        status = {"replicas": len(owned), "readyReplicas": ready,
                  "currentReplicas": len(owned),
                  "updatedReplicas": len(owned),
                  "observedGeneration": sts["metadata"].get("generation", 0)}
        if (sts.get("status") or {}) != status:
            def patch(o):
                o["status"] = status
                return o
            try:
                self.client.guaranteed_update(STATEFULSETS, ns, name, patch)
            except kv.NotFoundError:
                pass

    def _manage(self, key, sts, ns, name, want, parallel, owned) -> None:
        missing = [i for i in range(want) if i not in owned]
        extra = sorted((i for i in owned if i >= want), reverse=True)
        if missing:
            if parallel:
                self.expectations.expect_creations(key, len(missing))
                for i in missing:
                    self._safe_create(key, sts, i)
            else:
                # OrderedReady: only the lowest missing ordinal, and only
                # if every lower ordinal is running and ready
                i = missing[0]
                lower_ok = all(j in owned and pod_is_ready(owned[j])
                               for j in range(i))
                if lower_ok or i == 0:
                    self.expectations.expect_creations(key, 1)
                    self._safe_create(key, sts, i)
        elif extra:
            # scale down from the top, one at a time unless Parallel
            victims = extra if parallel else extra[:1]
            self.expectations.expect_deletions(key, len(victims))
            for i in victims:
                try:
                    self.client.delete(PODS, ns, f"{name}-{i}")
                except kv.NotFoundError:
                    self.expectations.deletion_observed(key)

    def _safe_create(self, key, sts, ordinal) -> None:
        try:
            if not self._create_pod(sts, ordinal):
                self.expectations.creation_observed(key)
        except Exception:
            self.expectations.creation_observed(key)
            raise

    def _create_pod(self, sts: Obj, ordinal: int) -> bool:
        ns, set_name = meta.namespace(sts), meta.name(sts)
        tmpl = (sts.get("spec") or {}).get("template") or {}
        pod = meta.new_object("Pod", f"{set_name}-{ordinal}", ns)
        tmpl_meta = tmpl.get("metadata") or {}
        pod["metadata"]["labels"] = dict(tmpl_meta.get("labels") or {})
        pod["metadata"]["labels"]["statefulset.kubernetes.io/pod-name"] = \
            meta.name(pod)
        pod["metadata"]["ownerReferences"] = [owner_ref(sts, "StatefulSet")]
        pod["spec"] = meta.deep_copy(tmpl.get("spec") or {"containers": [
            {"name": "c0", "image": "img"}]})
        pod["spec"]["hostname"] = meta.name(pod)
        pod["spec"]["subdomain"] = (sts.get("spec") or {}).get("serviceName", "")
        pod["spec"].setdefault("schedulerName", "default-scheduler")
        # stable storage: one PVC per volumeClaimTemplate, named
        # <claim>-<pod>; pre-existing PVCs are reused (identity survives)
        for vct in (sts.get("spec") or {}).get("volumeClaimTemplates", []):
            claim = meta.name(vct) or (vct.get("metadata") or {}).get("name", "data")
            pvc_name = f"{claim}-{meta.name(pod)}"
            pvc = meta.new_object("PersistentVolumeClaim", pvc_name, ns)
            pvc["spec"] = meta.deep_copy(vct.get("spec") or {})
            try:
                self.client.create(PVCS, pvc)
            except kv.AlreadyExistsError:
                pass
            pod["spec"].setdefault("volumes", []).append(
                {"name": claim,
                 "persistentVolumeClaim": {"claimName": pvc_name}})
        try:
            self.client.create(PODS, pod)
            return True
        except kv.AlreadyExistsError:
            return False
