"""StorageVersion publishing + garbage collection.

Reference: pkg/controller/storageversiongc/gc_controller.go — each
kube-apiserver publishes an identity Lease (kube-system, labeled
apiserver.kubernetes.io/identity=kube-apiserver) plus StorageVersion
objects recording, per resource, which encoding version THAT server
writes (serverStorageVersions entries keyed by apiServerID).  The GC
controller watches the identity leases: when a server's lease is deleted
or expires, its entries are stripped from every StorageVersion, and
StorageVersion objects left with no entries are deleted — so readers
always know the set of encodings possibly present in storage.

This control plane has one wire form per resource (SURVEY §2.5 —
code-generator N/A by design), so encodingVersion is always "v1"-shaped;
the machinery still matters for rolling multi-apiserver deployments,
which is why the VERDICT asked the row to stop being out of scope.
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import LEASES
from ..store import kv
from .base import Controller

logger = logging.getLogger(__name__)

STORAGEVERSIONS = "storageversions"
IDENTITY_LABEL = "apiserver.kubernetes.io/identity"
IDENTITY_VALUE = "kube-apiserver"
LEASE_DURATION = 60.0  # identity lease TTL (controller-manager default-ish)

# the resources an apiserver publishes storage versions for (one wire
# form each — the point is the per-server bookkeeping, not conversions)
PUBLISHED_RESOURCES = ("pods", "nodes", "services", "deployments",
                      "replicasets", "secrets", "configmaps")


def publish_identity(client, server_id: str) -> None:
    """Create/renew the apiserver identity Lease (kube-system)."""
    lease = meta.new_object("Lease", server_id, "kube-system")
    lease["metadata"]["labels"] = {IDENTITY_LABEL: IDENTITY_VALUE}
    now = time.time()
    lease["spec"] = {"holderIdentity": server_id, "renewTime": now,
                     "leaseDurationSeconds": LEASE_DURATION}
    try:
        client.create(LEASES, lease)
    except kv.AlreadyExistsError:
        def renew(cur):
            cur.setdefault("spec", {})["renewTime"] = time.time()
            cur["spec"]["holderIdentity"] = server_id
            return cur
        client.guaranteed_update(LEASES, "kube-system", server_id, renew)


def publish_storage_versions(client, server_id: str,
                             resources=PUBLISHED_RESOURCES,
                             encoding: str = "v1") -> None:
    """Upsert this server's serverStorageVersions entries."""
    for res in resources:
        name = f"core.{res}"
        entry = {"apiServerID": server_id, "encodingVersion": encoding,
                 "decodableVersions": [encoding]}
        try:
            sv = meta.new_object("StorageVersion", name, None)
            sv["status"] = {"storageVersions": [entry],
                            "commonEncodingVersion": encoding}
            client.create(STORAGEVERSIONS, sv)
        except kv.AlreadyExistsError:
            def upsert(cur, entry=entry):
                entries = (cur.setdefault("status", {})
                           .setdefault("storageVersions", []))
                entries[:] = [e for e in entries
                              if e.get("apiServerID") != server_id]
                entries.append(entry)
                encs = {e.get("encodingVersion") for e in entries}
                cur["status"]["commonEncodingVersion"] = (
                    encs.pop() if len(encs) == 1 else None)
                return cur
            client.guaranteed_update(STORAGEVERSIONS, "", name, upsert)


class StorageVersionGC(Controller):
    """Strip dead servers' entries; delete empty StorageVersions."""

    name = "storage-version-gc"
    workers = 1

    def __init__(self, client, factory, resync: float = 30.0):
        super().__init__(client, factory)
        self.lease_informer = factory.informer(LEASES)
        self.sv_informer = factory.informer(STORAGEVERSIONS)
        self.lease_informer.add_event_handler(self._on_lease_event)
        self.sv_informer.add_event_handler(
            lambda t, obj, old: self.enqueue_key("sweep"))
        self._resync = resync
        self._ticker: threading.Thread | None = None

    def run(self) -> None:
        super().run()
        # expiry produces no watch event: periodic sweep (gc_controller's
        # lease re-list cadence)
        def tick():
            while not self._stopped.wait(self._resync):
                self.enqueue_key("sweep")
        self._ticker = threading.Thread(target=tick, daemon=True,
                                        name=f"{self.name}-resync")
        self._ticker.start()

    def _on_lease_event(self, type_: str, obj: Obj, old) -> None:
        labels = meta.labels(obj)
        if labels.get(IDENTITY_LABEL) == IDENTITY_VALUE:
            self.enqueue_key("sweep")

    def _live_server_ids(self) -> set[str]:
        now = time.time()
        out = set()
        for lease in self.lease_informer.list("kube-system"):
            if meta.labels(lease).get(IDENTITY_LABEL) != IDENTITY_VALUE:
                continue
            spec = lease.get("spec") or {}
            renew = spec.get("renewTime", 0)
            ttl = spec.get("leaseDurationSeconds", LEASE_DURATION)
            if now <= renew + ttl:
                out.add(spec.get("holderIdentity") or meta.name(lease))
        return out

    def sync(self, key: str) -> None:
        live = self._live_server_ids()
        for sv in self.sv_informer.list(None):
            entries = (sv.get("status") or {}).get("storageVersions") or []
            keep = [e for e in entries if e.get("apiServerID") in live]
            if len(keep) == len(entries):
                continue
            name = meta.name(sv)
            if not keep:
                logger.info("storage-version-gc: deleting %s "
                            "(no live servers)", name)
                try:
                    self.client.delete(STORAGEVERSIONS, "", name)
                except kv.NotFoundError:
                    pass
                continue

            def strip(cur, keep_ids=live):
                entries = (cur.setdefault("status", {})
                           .setdefault("storageVersions", []))
                entries[:] = [e for e in entries
                              if e.get("apiServerID") in keep_ids]
                encs = {e.get("encodingVersion") for e in entries}
                cur["status"]["commonEncodingVersion"] = (
                    encs.pop() if len(encs) == 1 else None)
                return cur
            try:
                self.client.guaranteed_update(STORAGEVERSIONS, "", name,
                                              strip)
            except kv.NotFoundError:
                pass
