"""TTL controller.

Reference: pkg/controller/ttl/ttl_controller.go — annotates every Node with
`node.alpha.kubernetes.io/ttl`, the secret/configmap cache TTL kubelets may
use, scaled by cluster size (ttlBoundaries: 0s up to 100 nodes, 15s to 500,
30s to 1000, 60s to 2000, 300s above).
"""

from __future__ import annotations

import logging

from ..api import meta
from ..client.clientset import NODES
from ..store import kv
from .base import Controller, split_key

logger = logging.getLogger(__name__)

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"
# (max cluster size for this tier, ttl seconds) — ttl_controller.go:82
TTL_BOUNDARIES = [(100, 0), (500, 15), (1000, 30), (2000, 60)]
TTL_MAX = 300


class TTLController(Controller):
    name = "ttl"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.node_informer = factory.informer(NODES)
        self._last_ttl: int | None = None
        self.node_informer.add_event_handler(self._on_node)

    def _on_node(self, type_, node, old) -> None:
        # adds AND deletes can shift the cluster-size tier; when it moves,
        # every node's annotation is stale, not just the event's node
        ttl = self.desired_ttl()
        if ttl != self._last_ttl:
            self._last_ttl = ttl
            for n in self.node_informer.list(None):
                self.enqueue(n)
        if type_ != kv.DELETED:
            self.enqueue(node)

    def desired_ttl(self) -> int:
        n = len(self.node_informer.list(None))
        for bound, ttl in TTL_BOUNDARIES:
            if n <= bound:
                return ttl
        return TTL_MAX

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        node = self.node_informer.get("", name)
        if node is None:
            return
        want = str(self.desired_ttl())
        annotations = (node["metadata"].get("annotations") or {})
        if annotations.get(TTL_ANNOTATION) == want:
            return

        def patch(o):
            o["metadata"].setdefault("annotations", {})[TTL_ANNOTATION] = want
            return o
        try:
            self.client.guaranteed_update(NODES, "", name, patch)
        except kv.NotFoundError:
            pass
