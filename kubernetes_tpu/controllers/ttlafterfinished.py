"""TTL-after-finished controller.

Reference: pkg/controller/ttlafterfinished/ — Jobs with
spec.ttlSecondsAfterFinished are deleted TTL seconds after they reach
Complete/Failed.  Completion time comes from status.completionTime (we
stamp it when the condition appears if absent).
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import JOBS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv

logger = logging.getLogger(__name__)


def _finished_at(job: Obj) -> float | None:
    status = job.get("status") or {}
    conds = status.get("conditions") or []
    if not any(c.get("type") in ("Complete", "Failed")
               and c.get("status") == "True" for c in conds):
        return None
    ct = status.get("completionTime")
    return float(ct) if ct is not None else None


class TTLAfterFinishedController:
    name = "ttlafterfinished"

    def __init__(self, client: Client, factory: SharedInformerFactory,
                 tick: float = 5.0):
        self.client = client
        self.job_informer = factory.informer(JOBS)
        self.tick = tick
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # completionTime is stamped by the job controller in the same
        # status write that sets the Complete/Failed condition; stamping it
        # here too would race that writer and shift the TTL deadline.

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick):
            try:
                self.sweep_once(time.time())
            except Exception:  # noqa: BLE001
                logger.exception("ttl-after-finished sweep failed")

    def sweep_once(self, now: float) -> None:
        for job in self.job_informer.list(None):
            ttl = (job.get("spec") or {}).get("ttlSecondsAfterFinished")
            if ttl is None:
                continue
            done_at = _finished_at(job)
            if done_at is not None and now >= done_at + float(ttl):
                try:
                    self.client.delete(JOBS, meta.namespace(job),
                                       meta.name(job))
                except kv.NotFoundError:
                    pass
